"""Real polygon/RLE mask gt pipeline: host box-frame rasterization,
in-graph crop-resize targets, flip augmentation, loader assembly, and
the sample_rois gt_index consistency the mask loss depends on.

Expected values are derived from geometry (ellipse/triangle equations),
not from the implementation.
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np

from mx_rcnn_tpu.config import generate_config
from mx_rcnn_tpu.data.masks import (
    flip_segmentations,
    polygons_to_box_frame,
    record_gt_masks,
)
from mx_rcnn_tpu.data.synthetic import SyntheticDataset, shape_polygon, synthetic_image
from mx_rcnn_tpu.ops.mask_targets import crop_resize_masks, rasterize_box_masks


BOX = [10.0, 20.0, 73.0, 99.0]  # 64 x 80 px


class TestBoxFrameRasterization:
    def test_rect_polygon_fills_frame(self):
        bm = polygons_to_box_frame([shape_polygon("rect", BOX)], BOX, 64)
        assert bm.shape == (64, 64) and bm.all()

    def test_ellipse_matches_equation(self):
        """poly_fill of the 24-gon vs the exact ellipse equation on cell
        centers: ≥97% agreement (disagreement = the polygonal
        approximation near the rim)."""
        bm = polygons_to_box_frame([shape_polygon("ellipse", BOX)], BOX, 64)
        u = (np.arange(64) + 0.5) / 64 * 2 - 1
        exact = (u[None, :] ** 2 + u[:, None] ** 2) <= 1.0
        assert (bm.astype(bool) == exact).mean() > 0.97
        # and area ≈ pi/4 of the box
        assert abs(bm.mean() - np.pi / 4) < 0.03

    def test_triangle_matches_halfplane(self):
        """Apex-at-top triangle (t=0.5): covered cells lie under the two
        edges; area = 1/2 box."""
        bm = polygons_to_box_frame(
            [shape_polygon("triangle", BOX, t=0.5)], BOX, 64
        )
        assert abs(bm.mean() - 0.5) < 0.03
        # bottom row fully covered, top row (apex only) nearly empty
        assert bm[-1].mean() > 0.95
        assert bm[0].mean() < 0.05

    def test_multi_polygon_union(self):
        """Two disjoint rectangles in one segmentation OR together."""
        x1, y1, x2, y2 = BOX
        w = x2 - x1 + 1
        left = [x1, y1, x1 + w / 4, y1, x1 + w / 4, y2 + 1, x1, y2 + 1]
        right = [x2 + 1 - w / 4, y1, x2 + 1, y1, x2 + 1, y2 + 1, x2 + 1 - w / 4, y2 + 1]
        bm = polygons_to_box_frame([left, right], BOX, 64)
        assert bm[:, :14].all() and bm[:, -14:].all()
        assert not bm[:, 20:44].any()

    def test_rle_crowd_path(self):
        """RLE dict segmentation decodes through the crop-resize path."""
        from mx_rcnn_tpu.native import rle as rlelib

        full = np.zeros((120, 200), np.uint8)
        full[20:100, 10:74] = 1  # exactly BOX
        bm = polygons_to_box_frame(rlelib.encode(full), BOX, 32)
        assert bm.all()


class TestCropResizeMasks:
    def test_roi_equals_gt_box_reproduces_bitmap_pattern(self):
        """gt bitmap = left half set; roi == gt box → left half of the
        S-grid set."""
        bm = np.zeros((64, 64), np.uint8)
        bm[:, :32] = 1
        out = np.asarray(
            crop_resize_masks(
                jnp.asarray([BOX], jnp.float32),
                jnp.asarray([BOX], jnp.float32),
                jnp.asarray(bm[None]),
                28,
            )[0]
        )
        tgt = out >= 0.5
        assert tgt[:, :13].all() and not tgt[:, 15:].any()

    def test_sub_roi_zooms_into_bitmap(self):
        """roi = left half of the gt box over an ellipse bitmap → the
        left half-ellipse (compared against the equation)."""
        bm = polygons_to_box_frame([shape_polygon("ellipse", BOX)], BOX, 64)
        x1, y1, x2, y2 = BOX
        half = [x1, y1, x1 + (x2 - x1 + 1) / 2 - 1, y2]
        out = np.asarray(
            crop_resize_masks(
                jnp.asarray([half], jnp.float32),
                jnp.asarray([BOX], jnp.float32),
                jnp.asarray(bm[None]),
                28,
            )[0]
        )
        xs = -1 + (np.arange(28) + 0.5) / 28
        ys = (np.arange(28) + 0.5) / 28 * 2 - 1
        exact = (xs[None, :] ** 2 + ys[:, None] ** 2) <= 1.0
        assert ((out >= 0.5) == exact).mean() > 0.95

    def test_roi_outside_gt_box_is_empty(self):
        bm = np.ones((64, 64), np.uint8)
        out = np.asarray(
            crop_resize_masks(
                jnp.asarray([[200.0, 200.0, 260.0, 260.0]], jnp.float32),
                jnp.asarray([BOX], jnp.float32),
                jnp.asarray(bm[None]),
                14,
            )[0]
        )
        assert (out < 0.5).all()

    def test_all_ones_bitmap_agrees_with_rasterize_box_masks(self):
        """The rectangle special case: crop-resize of an all-ones bitmap
        must agree with rasterize_box_masks except at boundary cells."""
        rois = jnp.asarray(
            [[0.0, 0.0, 99.0, 99.0], [30.0, 40.0, 80.0, 95.0]], jnp.float32
        )
        gts = jnp.asarray([BOX, BOX], jnp.float32)
        ones = jnp.ones((2, 64, 64), jnp.uint8)
        a = np.asarray(crop_resize_masks(rois, gts, ones, 28)) >= 0.5
        b = np.asarray(rasterize_box_masks(rois, gts, 28)) > 0.5
        assert (a == b).mean() > 0.93


class TestFlip:
    def test_polygon_flip_mirrors_bitmap(self):
        poly = shape_polygon("triangle", BOX, t=0.3)
        width = 640
        flipped = flip_segmentations([[poly]], width)[0]
        fbox = [width - 1 - BOX[2], BOX[1], width - 1 - BOX[0], BOX[3]]
        a = polygons_to_box_frame([poly], BOX, 64)
        b = polygons_to_box_frame(flipped, fbox, 64)
        assert (b == a[:, ::-1]).all()

    def test_rle_flip_lazy(self):
        """RLE flip is a lazy tag (no decode/re-encode at roidb-prep
        time); rle_to_bitmap materializes the mirrored bitmap, and a
        double flip round-trips to the original."""
        from mx_rcnn_tpu.data.masks import rle_to_bitmap
        from mx_rcnn_tpu.native import rle as rlelib

        full = np.zeros((40, 60), np.uint8)
        full[5:20, 3:17] = 1
        enc = rlelib.encode(full)
        out = flip_segmentations([enc], 60)[0]
        assert out["counts"] == enc["counts"]  # no re-encode happened
        assert (rle_to_bitmap(out) == full[:, ::-1]).all()
        back = flip_segmentations([out], 60)[0]
        assert (rle_to_bitmap(back) == full).all()

    def test_append_flipped_flips_segmentation(self):
        ds = SyntheticDataset(
            num_images=2, num_classes=4, image_size=(128, 192), with_masks=True
        )
        from mx_rcnn_tpu.data.imdb import IMDB

        roidb = IMDB.append_flipped_images(ds.gt_roidb())
        orig, flip = roidb[0], roidb[2]
        assert flip["flipped"] and flip["segmentation"] is not None
        i = 0
        a = polygons_to_box_frame(
            orig["segmentation"][i], orig["boxes"][i], 48
        )
        b = polygons_to_box_frame(
            flip["segmentation"][i], flip["boxes"][i], 48
        )
        assert (b == a[:, ::-1]).all()

    def test_synthetic_flipped_render_matches_gt(self):
        """The flip-cancellation regression: a flipped synthetic record's
        rendered image must show the class color at the FLIPPED gt box
        (the loader must not flip an already-flip-rendered image)."""
        from mx_rcnn_tpu.data.imdb import IMDB
        from mx_rcnn_tpu.data.loader import _load_record_image
        from mx_rcnn_tpu.data.synthetic import class_color

        ds = SyntheticDataset(num_images=1, num_classes=4, image_size=(128, 192))
        roidb = IMDB.append_flipped_images(ds.gt_roidb())
        rec = roidb[1]
        assert rec["flipped"]
        im = _load_record_image(rec)
        x1, y1, x2, y2 = rec["boxes"][0].astype(int)
        cx, cy = (x1 + x2) // 2, (y1 + y2) // 2
        expected = class_color(int(rec["gt_classes"][0]))
        assert np.abs(im[cy, cx] - expected).max() < 12.0, (
            "flipped synthetic image content does not match flipped gt"
        )


class TestRecordAndLoader:
    def _cfg(self):
        cfg = generate_config("mask_resnet_fpn", "PascalVOC")
        return cfg.replace(
            SHAPE_BUCKETS=((128, 128),),
            dataset=dataclasses.replace(
                cfg.dataset, NUM_CLASSES=4, SCALES=((128, 128),), MAX_GT_BOXES=4
            ),
        )

    def test_record_gt_masks(self):
        ds = SyntheticDataset(
            num_images=1, num_classes=4, image_size=(128, 192),
            max_boxes=3, with_masks=True,
        )
        rec = ds.gt_roidb()[0]
        out = record_gt_masks(rec, 4, 32)
        assert out.shape == (4, 32, 32) and out.dtype == np.uint8
        n = len(rec["boxes"])
        assert out[:n].any(axis=(1, 2)).all(), "every gt has coverage"
        # box-only record → None
        rec2 = {k: v for k, v in rec.items() if k != "segmentation"}
        assert record_gt_masks(rec2, 4, 32) is None
        # per-gt None → rectangle (ones)
        rec3 = dict(rec)
        rec3["segmentation"] = [None] * n
        assert record_gt_masks(rec3, 4, 32)[:n].all()

    def test_trainloader_emits_gt_masks_for_mask_cfg(self):
        from mx_rcnn_tpu.data.loader import TrainLoader

        cfg = self._cfg()
        ds = SyntheticDataset(
            num_images=2, num_classes=4, image_size=(128, 128), with_masks=True
        )
        loader = TrainLoader(ds.gt_roidb(), cfg, batch_size=2, prefetch=0)
        batch = next(iter(loader))
        m = cfg.TRAIN.MASK_GT_SIZE
        assert batch["gt_masks"].shape == (2, 4, m, m)
        assert batch["gt_masks"].dtype == np.uint8
        # valid gts have non-trivial (not all-ones, not empty) bitmaps
        # at least somewhere — polygons include ellipses/triangles
        gv = batch["gt_valid"]
        covered = batch["gt_masks"][gv].mean(axis=(1, 2))
        assert (covered > 0.2).all() and (covered < 1.01).all()

    def test_non_mask_cfg_has_no_gt_masks(self):
        from mx_rcnn_tpu.data.loader import TrainLoader

        cfg = generate_config("resnet_fpn", "PascalVOC").replace(
            SHAPE_BUCKETS=((128, 128),),
            dataset=dataclasses.replace(
                generate_config("resnet_fpn", "PascalVOC").dataset,
                NUM_CLASSES=4, SCALES=((128, 128),), MAX_GT_BOXES=4,
            ),
        )
        ds = SyntheticDataset(num_images=2, num_classes=4, image_size=(128, 128))
        loader = TrainLoader(ds.gt_roidb(), cfg, batch_size=2, prefetch=0)
        batch = next(iter(loader))
        assert "gt_masks" not in batch


class TestGtIndexConsistency:
    def test_label_matches_gt_index_class(self):
        """For every fg roi, samples.labels must equal the class of the
        gt at samples.gt_index — the invariant the mask loss relies on."""
        from mx_rcnn_tpu.ops.targets import sample_rois

        cfg = generate_config("resnet", "PascalVOC")
        cfg = cfg.replace(
            dataset=dataclasses.replace(cfg.dataset, NUM_CLASSES=8),
            TRAIN=dataclasses.replace(cfg.TRAIN, BATCH_ROIS=64),
        )
        rng = np.random.RandomState(0)
        p, g = 120, 6
        gt = np.zeros((g, 5), np.float32)
        for i in range(g):
            x1, y1 = rng.randint(0, 300, 2)
            gt[i] = [x1, y1, x1 + rng.randint(30, 120), y1 + rng.randint(30, 120),
                     rng.randint(1, 8)]
        rois = np.zeros((p, 4), np.float32)
        for i in range(p):
            j = rng.randint(g)
            jit = rng.randint(-25, 25, 4)
            rois[i] = gt[j, :4] + jit
        rois[:, 2] = np.maximum(rois[:, 2], rois[:, 0] + 1)
        rois[:, 3] = np.maximum(rois[:, 3], rois[:, 1] + 1)

        s = sample_rois(
            jnp.asarray(rois), jnp.ones((p,), bool),
            jnp.asarray(gt), jnp.ones((g,), bool),
            jax.random.key(3), cfg,
        )
        labels = np.asarray(s.labels)
        gidx = np.asarray(s.gt_index)
        fg = labels > 0
        assert fg.sum() > 0
        np.testing.assert_array_equal(labels[fg], gt[gidx[fg], 4].astype(np.int32))


class TestSyntheticSegmEval:
    def test_perfect_predictions_score_one(self):
        """Feeding the gt itself (boxes + exact polygon RLEs) through the
        segm evaluator must yield AP = 1."""
        from mx_rcnn_tpu.native import rle as rlelib

        ds = SyntheticDataset(
            num_images=3, num_classes=4, image_size=(128, 192),
            max_boxes=2, with_masks=True, seed=5,
        )
        roidb = ds.gt_roidb()
        k = ds.num_classes
        all_boxes = [[np.zeros((0, 5), np.float32) for _ in roidb] for _ in range(k)]
        all_masks = [[[] for _ in roidb] for _ in range(k)]
        for i, rec in enumerate(roidb):
            for box, cls, segm in zip(
                rec["boxes"], rec["gt_classes"], rec["segmentation"]
            ):
                det = np.concatenate([box, [0.9]]).astype(np.float32)[None]
                all_boxes[cls][i] = np.concatenate([all_boxes[cls][i], det])
                all_masks[cls][i].append(
                    rlelib.from_polygons(segm, rec["height"], rec["width"])
                )
        stats = ds.evaluate_detections(all_boxes, all_masks=all_masks)
        assert stats["mAP"] > 0.99
        assert stats["segm_AP"] > 0.99
