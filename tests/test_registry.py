"""Fault matrix for the model registry + hot-swap pipeline (ISSUE 7),
CPU-only and fast.

Same philosophy as ``tests/test_replica.py``: every test drives the REAL
``ModelRegistry`` / ``SwapController`` / engine machinery — including
real orbax checkpoints through ``core/checkpoint.py``'s manifest gate —
and only the predict path is a numpy stub (:class:`FakeSwapRunner`)
whose "detections" are a pure deterministic digest of the batch pixels
AND the live params, so a version swap is visible in every result byte
and a request served by the wrong version shows up as a digest mismatch,
not a flake.

The invariants under test are the ISSUE 7 acceptance criteria: a swap
under load loses zero requests and requests served entirely before
(after) the swap window are byte-identical to an all-v1 (all-v2) run; an
injected verify/warm/canary failure rolls back to the previous LIVE
version with the candidate retired and its staged buffers discarded;
``stop(drain=True)`` during an in-flight swap cancels it cleanly (no
warm work after stop returns); and two model families share one batcher
with per-(model, bucket) compile accounting.
"""

import os
import shutil
import threading
import time

import numpy as np
import pytest

from mx_rcnn_tpu.core.checkpoint import (
    CheckpointCorrupt,
    restore_tree,
    save_checkpoint,
    verify_manifest,
)
from mx_rcnn_tpu.serve.batcher import Request
from mx_rcnn_tpu.serve.buckets import BucketLadder, CompileCache
from mx_rcnn_tpu.serve.engine import ServingEngine
from mx_rcnn_tpu.serve.loadgen import run_load
from mx_rcnn_tpu.serve.registry import (
    ModelRegistry,
    SwapCancelled,
    SwapInProgress,
    SwapRolledBack,
    UnknownModel,
    VersionState,
)
from mx_rcnn_tpu.serve.replica import HealthPolicy, Replica, ReplicaState
from mx_rcnn_tpu.utils import faults


@pytest.fixture(autouse=True)
def _lock_order_check(monkeypatch):
    """Run the whole fault matrix with the R4 runtime counterpart on:
    every serve-stack lock becomes an order-asserting proxy
    (analysis/lockcheck.py) that raises LockOrderViolation at the
    acquire that would close a cycle."""
    from mx_rcnn_tpu.analysis import lockcheck

    monkeypatch.setenv("MX_RCNN_LOCK_CHECK", "1")
    lockcheck.reset()
    yield

LADDER = ((32, 32), (48, 64))
SIZES = ((24, 24), (32, 48), (16, 16))  # exercises both buckets

FAST = HealthPolicy(
    stall_timeout=0.3,
    fail_threshold=2,
    breaker_backoff=0.05,
    breaker_max_backoff=0.2,
    flap_window=10.0,
)


def params_tree(w: float):
    """A registry params tree: one scalar leaf that changes per version
    (structure/shape/dtype identical, so the swap signature gate passes)."""
    return {"w": np.array([w], np.float32)}


def _digest(images: np.ndarray, w: float) -> np.ndarray:
    """Per-slot digest, a pure function of the slot pixels and the live
    version's ``w`` — the single computation shared by the fake's predict
    and the tests' expectations, so comparisons are byte-exact."""
    im = images.astype(np.float64)
    return np.stack(
        [
            im.sum(axis=(1, 2, 3)) * (1.0 + w),
            (im * im).sum(axis=(1, 2, 3)) + w,
        ],
        axis=1,
    )


class FakeSwapRunner:
    """Registry-backed runner stub implementing the full swap target
    surface (``warm_version`` / ``canary`` / ``discard_version``) with
    the real sync semantics: predict resolves the registry's live
    pointer per batch, adopting a staged tree on version mismatch."""

    def __init__(self, registry, index: int = 0, service_s: float = 0.0,
                 warm_delay_s: float = 0.0):
        self.registry = registry
        self.default_model = registry.default_model
        self.index = index
        self.service_s = service_s
        self.warm_delay_s = warm_delay_s
        self.ladder = BucketLadder(LADDER)
        self.max_batch = 2
        self.cfg = None
        self.compile_cache = CompileCache()
        self.served_buckets = {}
        self.swaps_applied = 0
        self.warm_started = threading.Event()
        self.warm_rungs_done = 0
        self.warmed_plan = None  # what the last warmup() actually warmed
        self._versions = {}
        self._params = {}
        self._staged = {}
        self._lock = threading.Lock()

    def _mid(self, model):
        return self.default_model if model is None else model

    def _sync(self, mid):
        live = self.registry.live(mid)
        with self._lock:
            if self._versions.get(mid) == live.version:
                return
            staged = self._staged.pop((mid, live.version), None)
            for k in [k for k in self._staged if k[0] == mid]:
                self._staged.pop(k, None)
            self._params[mid] = (
                staged if staged is not None else live.params
            )
            self._versions[mid] = live.version
            self.swaps_applied += 1

    # ---- runner facade (same shapes as tests/test_replica.FakeRunner)
    def warmup(self, buckets=None, models=None) -> int:
        if isinstance(buckets, dict):
            per = {m: sorted(bs) for m, bs in buckets.items() if bs}
            if not per:
                per = {m: list(self.ladder)
                       for m in self.registry.model_ids()}
        elif buckets is not None:
            per = {m: sorted(buckets)
                   for m in (models or [self.default_model])}
        else:
            per = {m: list(self.ladder)
                   for m in (models or self.registry.model_ids())}
        self.warmed_plan = {m: list(bs) for m, bs in per.items()}
        for m, rungs in per.items():
            self._sync(m)
            for bh, bw in rungs:
                self.compile_cache.record(
                    (m, (self.max_batch, bh, bw, 3), "f32")
                )
        return self.compile_cache.misses

    def make_request(self, im, deadline=None, model=None) -> Request:
        h, w = im.shape[:2]
        bh, bw = self.ladder.select(h, w)
        canvas = np.zeros((bh, bw, 3), np.float32)
        canvas[:h, :w] = im
        return Request(
            image=canvas,
            im_info=np.array([h, w, 1.0], np.float32),
            orig_hw=(h, w),
            bucket=(bh, bw),
            deadline=deadline,
            model=model,
        )

    def assemble(self, requests):
        mid = requests[0].model
        if any(r.model != mid for r in requests):
            raise ValueError("mixed models in one batch")
        images = [r.image for r in requests]
        while len(images) < self.max_batch:
            images.append(images[0])
        return {
            "images": np.stack(images),
            "im_info": np.stack(
                [r.im_info for r in requests]
                + [requests[0].im_info] * (self.max_batch - len(requests))
            ),
            "orig_hw": np.array(
                [r.orig_hw for r in requests]
                + [requests[0].orig_hw] * (self.max_batch - len(requests))
            ),
        }

    def run(self, batch, model=None):
        mid = self._mid(model)
        self._sync(mid)
        if self.service_s:
            time.sleep(self.service_s)
        self.compile_cache.record((mid, batch["images"].shape, "f32"))
        w = float(np.asarray(self._params[mid]["w"]).ravel()[0])
        self.served_buckets.setdefault(mid, set()).add(
            tuple(batch["images"].shape[1:3])
        )
        return {"digest": _digest(batch["images"], w)}

    def detections_for(self, out, batch, index, orig_hw=None, thresh=None,
                       model=None):
        return [out["digest"][index].copy()]

    # ---- swap target surface
    def warm_version(self, model, version, params, buckets=None, abort=None):
        mid = self._mid(model)
        self.warm_started.set()
        if abort is not None:
            abort()
        if buckets is None:
            buckets = sorted(self.served_buckets.get(mid, ())) or list(
                self.ladder
            )
        warmed = 0
        for _ in buckets:
            if abort is not None:
                abort()
            if self.warm_delay_s:
                time.sleep(self.warm_delay_s)
            warmed += 1
            self.warm_rungs_done += 1
        self._staged[(mid, int(version))] = params
        return warmed

    def canary(self, model=None):
        mid = self._mid(model)
        served = sorted(self.served_buckets.get(mid, ()))
        bh, bw = served[0] if served else next(iter(self.ladder))
        batch = {
            "images": np.zeros((self.max_batch, bh, bw, 3), np.float32),
            "im_info": np.tile(
                np.array([bh, bw, 1.0], np.float32), (self.max_batch, 1)
            ),
            "orig_hw": np.tile(
                np.array([bh, bw], np.float32), (self.max_batch, 1)
            ),
        }
        self.run(batch, model=None if mid == self.default_model else mid)
        return 1

    def discard_version(self, model, version):
        self._staged.pop((self._mid(model), int(version)), None)


def make_registry(models=(("det", 1.0),)):
    reg = ModelRegistry()
    for mid, w in models:
        reg.register(mid, model=None, cfg=None, params=params_tree(w))
    return reg


def expected(im: np.ndarray, w: float) -> np.ndarray:
    bh, bw = BucketLadder(LADDER).select(*im.shape[:2])
    canvas = np.zeros((bh, bw, 3), np.float32)
    canvas[: im.shape[0], : im.shape[1]] = im
    return _digest(canvas[None], w)[0]


def wait_for(pred, timeout=5.0, msg="condition"):
    t_end = time.monotonic() + timeout
    while time.monotonic() < t_end:
        if pred():
            return
        time.sleep(0.01)
    raise AssertionError(f"timed out waiting for {msg}")


@pytest.fixture
def no_faults(monkeypatch):
    monkeypatch.delenv(faults.ENV_VAR, raising=False)
    faults.reset()
    yield
    faults.reset()


@pytest.fixture(scope="module")
def ckpts(tmp_path_factory):
    """Two committed orbax dumps with the registry tree shape
    (``{"params": {"w": ...}}``): the v2 and v3 swap candidates."""
    root = tmp_path_factory.mktemp("registry-ckpts")
    out = {}
    for name, w in (("v2", 2.0), ("v3", 3.0)):
        out[name] = save_checkpoint(
            str(root / name), {"params": params_tree(w)}, 1
        )
    return out


# --------------------------------------------------- verify_manifest gate

def test_verify_manifest_matrix(tmp_path, no_faults):
    good = save_checkpoint(str(tmp_path / "ok"), {"params": params_tree(5.0)}, 1)
    man = verify_manifest(good)
    assert man["checksum"] and man["files"]
    # the no-reload fast path agrees with the self-restoring path
    assert verify_manifest(good, tree=restore_tree(good)) == man

    # missing manifest
    nomani = str(tmp_path / "nomani")
    shutil.copytree(good, nomani)
    os.remove(os.path.join(nomani, "manifest.json"))
    with pytest.raises(CheckpointCorrupt, match="manifest"):
        verify_manifest(nomani)

    # truncated data file (size disagrees with the manifest record)
    trunc = str(tmp_path / "trunc")
    shutil.copytree(good, trunc)
    rel = next(iter(verify_manifest(good)["files"]))
    with open(os.path.join(trunc, rel), "ab") as f:
        f.write(b"x")
    with pytest.raises(CheckpointCorrupt, match="truncated"):
        verify_manifest(trunc)

    # checksum tampered: files intact, digest disagrees
    bad = str(tmp_path / "badsum")
    shutil.copytree(good, bad)
    import json

    mpath = os.path.join(bad, "manifest.json")
    with open(mpath) as f:
        m = json.load(f)
    m["checksum"] = "0" * 64
    with open(mpath, "w") as f:
        json.dump(m, f)
    with pytest.raises(CheckpointCorrupt, match="checksum"):
        verify_manifest(bad)


# ------------------------------------------------------- fault grammar

def test_swap_fault_grammar_and_hook(monkeypatch):
    specs = faults._parse("swap_verify_fail@1,canary_fail@*,swap_warm_fail@2")
    assert specs[0].key == 1 and specs[1].key is None and specs[2].key == 2
    monkeypatch.setenv(faults.ENV_VAR, "swap_warm_fail@2x1,canary_fail@*")
    faults.reset()
    faults.swap_fault("warm", 1)        # wrong ordinal: no-op
    with pytest.raises(faults.InjectedSwapFault):
        faults.swap_fault("warm", 2)
    faults.swap_fault("warm", 2)        # x1: exhausted
    for ordinal in (1, 7):              # wildcard matches every swap
        with pytest.raises(faults.InjectedSwapFault):
            faults.swap_fault("canary", ordinal)
    faults.reset()


# ------------------------------------------------------ swap happy path

def test_swap_under_load_zero_lost_and_byte_identical(no_faults, ckpts):
    reg = make_registry()
    runner = FakeSwapRunner(reg, service_s=0.002)
    eng = ServingEngine(runner, max_linger=0.001, max_queue=64).start()
    try:
        N = 60
        report = {}

        def load():
            report.update(run_load(
                eng, num_requests=N, concurrency=4, sizes=SIZES, seed=7,
                collect=True,
            ))

        t = threading.Thread(target=load)
        t.start()
        wait_for(lambda: eng.metrics.completed >= N // 4, msg="mid-load")
        t_sw0 = time.monotonic()
        result = eng.swap("det", ckpts["v2"], block=True, timeout=30)
        t_sw1 = time.monotonic()
        t.join()

        assert result["model"] == "det" and result["version"] == 2
        assert result["previous"] == 1 and result["warmed"] >= 1
        assert report["outcomes"]["ok"] == N
        assert report["outcomes"]["error"] == 0
        snap = eng.snapshot()
        assert snap["requests"]["failed"] == 0
        assert snap["registry"]["swaps"]["completed"] == 1
        assert snap["registry"]["models"]["det"]["live_version"] == 2
        assert runner.swaps_applied >= 2  # initial slot sync + the swap

        # classify by the per-request submit/done timestamps: entirely
        # before the swap started → v1 bytes; submitted after the swap
        # returned → v2 bytes; straddling → exactly one of the two
        # (exactly-once: never a mixture, never a loss)
        sizes_rng = np.random.RandomState(7)
        req_sizes = [SIZES[sizes_rng.randint(len(SIZES))] for _ in range(N)]
        from mx_rcnn_tpu.serve.loadgen import synthetic_image

        pre = post = straddle = 0
        for i in range(N):
            kind, dets = report["_results"][i]
            assert kind == "ok", f"request {i} resolved {kind}"
            got = dets[0].tobytes()
            h, w = req_sizes[i]
            im = synthetic_image(i, h, w, 7)
            v1 = expected(im, 1.0).tobytes()
            v2 = expected(im, 2.0).tobytes()
            t_submit, t_done = report["_times"][i]
            if t_done <= t_sw0:
                assert got == v1, f"pre-swap request {i} not v1 bytes"
                pre += 1
            elif t_submit >= t_sw1:
                assert got == v2, f"post-swap request {i} not v2 bytes"
                post += 1
            else:
                assert got in (v1, v2), f"straddling request {i} mixed"
                straddle += 1
        assert pre > 0 and post > 0, (pre, straddle, post)
        # retired v1 released its params (PR 4 free-the-retired discipline)
        v1_ver = reg.entry("det").versions[0]
        assert v1_ver.state is VersionState.RETIRED and v1_ver.params is None
        assert snap["registry"]["versions_released"] == 1
    finally:
        eng.stop()


def test_swap_is_zero_compile_and_admin_surface(no_faults, ckpts):
    reg = make_registry()
    runner = FakeSwapRunner(reg)
    eng = ServingEngine(runner, max_linger=0.0).start()
    try:
        misses0 = runner.compile_cache.misses
        assert misses0 == len(LADDER)
        fut = eng.submit(np.ones((24, 24, 3), np.float32))
        np.testing.assert_array_equal(
            fut.result(5)[0], expected(np.ones((24, 24, 3), np.float32), 1.0)
        )
        out = eng.admin(f"swap det {ckpts['v2']}")
        assert out["version"] == 2
        # post-swap traffic hits only already-recorded signatures
        fut = eng.submit(np.ones((24, 24, 3), np.float32))
        np.testing.assert_array_equal(
            fut.result(5)[0], expected(np.ones((24, 24, 3), np.float32), 2.0)
        )
        assert runner.compile_cache.misses == misses0
        models = eng.admin("models")
        assert models["models"]["det"]["live_version"] == 2
        with pytest.raises(ValueError):
            eng.admin("bogus cmd")
    finally:
        eng.stop()


# ------------------------------------------------------ rollback matrix

@pytest.mark.parametrize(
    "kind,stage",
    [
        ("swap_verify_fail", "verify"),
        ("swap_warm_fail", "warm"),
        ("canary_fail", "canary"),
    ],
)
def test_injected_fault_rolls_back_to_previous_live(
    monkeypatch, ckpts, kind, stage
):
    monkeypatch.setenv(faults.ENV_VAR, f"{kind}@1")
    faults.reset()
    try:
        reg = make_registry()
        runner = FakeSwapRunner(reg)
        eng = ServingEngine(runner, max_linger=0.0).start()
        try:
            im = np.ones((24, 24, 3), np.float32)
            np.testing.assert_array_equal(
                eng.submit(im).result(5)[0], expected(im, 1.0)
            )
            with pytest.raises(SwapRolledBack) as exc:
                eng.swap("det", ckpts["v2"], block=True, timeout=30)
            assert exc.value.stage == stage
            assert isinstance(exc.value.cause, faults.InjectedSwapFault)
            # previous LIVE still serves, byte-identical
            assert reg.live("det").version == 1
            np.testing.assert_array_equal(
                eng.submit(im).result(5)[0], expected(im, 1.0)
            )
            # candidate retired + released; staged buffers discarded
            cand = reg.entry("det").versions[1]
            assert cand.state is VersionState.RETIRED and cand.params is None
            assert not runner._staged
            snap = reg.snapshot()
            assert snap["swaps"]["rolled_back"] == 1
            assert snap["swaps"]["completed"] == 0
            assert not snap["models"]["det"]["swap_in_flight"]
            # the registry is not wedged: swap #2 (no fault keyed) lands
            result = eng.swap("det", ckpts["v3"], block=True, timeout=30)
            assert result["version"] == 3 and reg.live("det").version == 3
            np.testing.assert_array_equal(
                eng.submit(im).result(5)[0], expected(im, 3.0)
            )
        finally:
            eng.stop()
    finally:
        faults.reset()


def test_corrupt_checkpoint_rolls_back_at_verify(no_faults, tmp_path, ckpts):
    bad = str(tmp_path / "bad")
    shutil.copytree(ckpts["v2"], bad)
    os.remove(os.path.join(bad, "manifest.json"))
    reg = make_registry()
    runner = FakeSwapRunner(reg)
    ctrl = reg.swap("det", bad, target=runner)
    with pytest.raises(SwapRolledBack) as exc:
        ctrl.result(30)
    assert isinstance(exc.value.cause, CheckpointCorrupt)
    assert reg.live("det").version == 1


def test_structure_mismatch_rejected_before_device(no_faults, tmp_path):
    # candidate with a DIFFERENT tree shape: the signature gate must
    # refuse it (a swap is never allowed to force a recompile)
    ck = save_checkpoint(
        str(tmp_path / "misshape"),
        {"params": {"w": np.zeros((2, 2), np.float32)}}, 1,
    )
    reg = make_registry()
    runner = FakeSwapRunner(reg)
    with pytest.raises(SwapRolledBack, match="verify"):
        reg.swap("det", ck, target=runner, block=True, timeout=30)
    assert not runner.warm_started.is_set()  # never reached the target
    assert reg.live("det").version == 1


def test_second_swap_while_in_flight_rejected(no_faults, ckpts):
    reg = make_registry()
    runner = FakeSwapRunner(reg, warm_delay_s=0.15)
    ctrl = reg.swap("det", ckpts["v2"], target=runner)
    try:
        wait_for(runner.warm_started.is_set, msg="warm start")
        with pytest.raises(SwapInProgress):
            reg.swap("det", ckpts["v3"], target=runner)
    finally:
        ctrl.result(30)
    assert reg.live("det").version == 2
    assert reg.snapshot()["swaps"]["started"] == 1


# -------------------------------------------------------- stop interlock

def test_stop_during_swap_cancels_cleanly(no_faults, ckpts):
    reg = make_registry()
    runner = FakeSwapRunner(reg, warm_delay_s=0.1)
    eng = ServingEngine(runner, max_linger=0.0).start()
    ctrl = eng.swap("det", ckpts["v2"])
    wait_for(runner.warm_started.is_set, msg="warm start")
    eng.stop(drain=True)
    # the interlock waited for the controller thread: no orphaned warmup
    assert ctrl.done() and not ctrl._thread.is_alive()
    with pytest.raises(SwapCancelled):
        ctrl.result(0)
    assert reg.swaps_in_flight() == 0
    snap = reg.snapshot()
    assert snap["swaps"]["cancelled"] == 1
    assert reg.live("det").version == 1
    cand = reg.entry("det").versions[1]
    assert cand.state is VersionState.RETIRED
    assert not runner._staged
    # no warm work lands after stop returns (the no-post-stop-device_put
    # contract: abort raises before each rung's placement)
    done_at_stop = runner.warm_rungs_done
    time.sleep(0.3)
    assert runner.warm_rungs_done == done_at_stop


# ------------------------------------------------------------- tenancy

def test_multi_model_routing_isolation(no_faults, ckpts):
    reg = make_registry((("alpha", 1.0), ("beta", 10.0)))
    runner = FakeSwapRunner(reg)
    eng = ServingEngine(runner, max_linger=0.001).start()
    try:
        # cold start: per-(model, bucket) signatures, once each
        assert runner.compile_cache.misses == 2 * len(LADDER)
        im = np.ones((24, 24, 3), np.float32)
        futs = {
            ("alpha", i): eng.submit(im, model="alpha") for i in range(3)
        }
        futs.update(
            {("beta", i): eng.submit(im, model="beta") for i in range(3)}
        )
        fut_default = eng.submit(im)  # model-less → default (first) family
        for (mid, _), f in futs.items():
            np.testing.assert_array_equal(
                f.result(5)[0], expected(im, 1.0 if mid == "alpha" else 10.0)
            )
        np.testing.assert_array_equal(
            fut_default.result(5)[0], expected(im, 1.0)
        )
        # steady state: no new signatures from either family
        assert runner.compile_cache.misses == 2 * len(LADDER)
        with pytest.raises(UnknownModel):
            eng.submit(im, model="gamma")
        snap = eng.snapshot()
        assert snap["requests"]["rejected"] == 1
        assert snap["models"]["alpha"]["completed"] == 3
        assert snap["models"]["beta"]["completed"] == 3

        # swapping beta must not move alpha: alpha bytes unchanged,
        # beta bytes flip to the candidate's params
        out = eng.swap("beta", ckpts["v2"], block=True, timeout=30)
        assert out["model"] == "beta" and out["version"] == 2
        np.testing.assert_array_equal(
            eng.submit(im, model="alpha").result(5)[0], expected(im, 1.0)
        )
        np.testing.assert_array_equal(
            eng.submit(im, model="beta").result(5)[0], expected(im, 2.0)
        )
        assert reg.live("alpha").version == 1
        assert reg.live("beta").version == 2
    finally:
        eng.stop()


def test_batcher_never_mixes_models(no_faults):
    reg = make_registry((("alpha", 1.0), ("beta", 10.0)))
    runner = FakeSwapRunner(reg)
    a = runner.make_request(np.ones((24, 24, 3), np.float32), model="alpha")
    b = runner.make_request(np.ones((24, 24, 3), np.float32), model="beta")
    with pytest.raises(ValueError, match="mixed models"):
        runner.assemble([a, b])
    from mx_rcnn_tpu.serve.batcher import DynamicBatcher

    batcher = DynamicBatcher(max_batch=2, max_linger=0.0)
    batcher.submit(a)
    batcher.submit(b)
    first = batcher.next_batch()
    second = batcher.next_batch()
    assert len(first) == 1 and len(second) == 1
    assert {first[0].model, second[0].model} == {"alpha", "beta"}


# ------------------------------------------- per-bucket warm partitioning

def test_recovery_rewarms_only_served_buckets(no_faults):
    reg = make_registry()
    built = []

    def factory(index):
        r = FakeSwapRunner(reg, index=index)
        built.append(r)
        return r

    rep = Replica(0, factory, policy=FAST)
    try:
        wait_for(lambda: rep.state is ReplicaState.HEALTHY, msg="warm")
        # traffic on ONE rung only
        im = np.ones((24, 24, 3), np.float32)
        runner0 = rep.runner
        batch = runner0.assemble([runner0.make_request(im)])
        rep.submit(batch).future.result(5)
        assert runner0.served_buckets == {"det": {(32, 32)}}
        rep.drain()
        wait_for(
            lambda: rep.state is ReplicaState.HEALTHY and len(built) == 2,
            msg="rejoin",
        )
        # the rebuilt runner warmed exactly the served partition
        assert built[1].warmed_plan == {"det": [(32, 32)]}
        assert rep.partial_rewarms == 1 and rep.last_rewarm_rungs == 1
        # an un-served rung still works (lazy warm on first dispatch)
        im2 = np.ones((32, 48, 3), np.float32)
        batch2 = rep.runner.assemble([rep.runner.make_request(im2)])
        d = rep.submit(batch2)
        np.testing.assert_array_equal(
            rep.runner.detections_for(d.future.result(5), batch2, 0)[0],
            expected(im2, 1.0),
        )
    finally:
        rep.stop()


# --------------------------------------------------------- observability

def test_registry_snapshot_and_transition_log(no_faults, ckpts):
    reg = make_registry()
    runner = FakeSwapRunner(reg)
    runner.warmup()
    result = reg.swap("det", ckpts["v2"], target=runner, block=True,
                      timeout=30)
    assert result["digest"]  # manifest checksum rode along
    snap = reg.snapshot()
    det = snap["models"]["det"]
    assert det["live_version"] == 2
    states = [v["state"] for v in det["versions"]]
    assert states == ["retired", "live"]
    v2 = det["versions"][1]
    walk = [t["to"] for t in v2["transitions"]]
    assert walk == ["verifying", "warming", "live"]
    assert det["versions"][0]["released"] is True
    assert snap["versions_released"] == 1
    assert snap["swaps"] == {
        "started": 1, "completed": 1, "rolled_back": 0, "cancelled": 0,
        "in_flight": 0,
    }
