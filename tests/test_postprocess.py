"""Device-side eval postprocess (ops/postprocess.py) vs the host
reference loop, and the uint8-transfer normalize-on-device path.

The host loop (im_detect → per-class threshold → C NMS) is the
reference semantics; the device path must reproduce its detections
exactly (same keep sets, same boxes modulo float association).
"""

import dataclasses

import jax.numpy as jnp
import numpy as np
import pytest

from mx_rcnn_tpu.config import generate_config
from mx_rcnn_tpu.core.tester import im_detect
from mx_rcnn_tpu.native.hostops import nms_host
from mx_rcnn_tpu.ops.postprocess import make_test_postprocess


def _fake_outputs(rng, b=2, r=64, k=5):
    """Plausible raw head outputs: clustered rois + noisy deltas so NMS
    has real suppression work to do."""
    rois = np.zeros((b, r, 4), np.float32)
    centers = rng.rand(b, r, 2) * 300 + 50
    wh = rng.rand(b, r, 2) * 80 + 20
    rois[..., 0] = centers[..., 0] - wh[..., 0] / 2
    rois[..., 1] = centers[..., 1] - wh[..., 1] / 2
    rois[..., 2] = centers[..., 0] + wh[..., 0] / 2
    rois[..., 3] = centers[..., 1] + wh[..., 1] / 2
    valid = rng.rand(b, r) > 0.1
    logits = rng.randn(b, r, k).astype(np.float32) * 2
    cls_prob = np.exp(logits) / np.exp(logits).sum(-1, keepdims=True)
    deltas = (rng.randn(b, r, 4 * k) * 0.1).astype(np.float32)
    im_info = np.tile([400.0, 500.0, 1.6], (b, 1)).astype(np.float32)
    return {
        "rois": rois,
        "roi_valid": valid,
        "cls_prob": cls_prob.astype(np.float32),
        "bbox_deltas": deltas,
    }, im_info


class TestDevicePostprocessEquivalence:
    @pytest.mark.parametrize("seed", [0, 1, 2])
    def test_matches_host_reference_loop(self, seed):
        cfg = generate_config("resnet50", "PascalVOC")
        te = cfg.TEST
        thresh = 0.05
        k = 5
        rng = np.random.RandomState(seed)
        out, im_info = _fake_outputs(rng, k=k)
        orig_hw = np.stack(
            [np.floor(im_info[:, 0] / im_info[:, 2]),
             np.floor(im_info[:, 1] / im_info[:, 2])], axis=1
        ).astype(np.float32)
        fn = make_test_postprocess(cfg, k, thresh, max_out=32)
        dev = fn({kk: jnp.asarray(v) for kk, v in out.items()},
                 jnp.asarray(im_info), jnp.asarray(orig_hw))

        for b in range(out["rois"].shape[0]):
            det = im_detect(out, im_info[b], tuple(orig_hw[b]), index=b)
            scores, boxes = det["scores"], det["boxes"]
            for j in range(1, k):
                keep = np.where(scores[:, j] > thresh)[0]
                cls = np.hstack(
                    [boxes[keep, j * 4:(j + 1) * 4], scores[keep, j:j + 1]]
                ).astype(np.float32)
                host = cls[nms_host(cls, te.NMS)]
                host = host[np.argsort(-host[:, 4])]

                m = np.asarray(dev["det_valid"][b][j - 1]).astype(bool)
                db = np.asarray(dev["det_boxes"][b][j - 1][m])
                ds = np.asarray(dev["det_scores"][b][j - 1][m])
                order = np.argsort(-ds)
                assert len(ds) == len(host), (
                    f"img {b} cls {j}: device kept {len(ds)}, host {len(host)}"
                )
                np.testing.assert_allclose(ds[order], host[:, 4], rtol=1e-5)
                np.testing.assert_allclose(
                    db[order], host[:, :4], rtol=1e-4, atol=1e-3
                )


def _fake_mask_outputs(rng, b=2, r=64, k=5, s=14):
    """Raw head outputs plus a per-roi (S, S, K) mask-logit stack."""
    out, im_info = _fake_outputs(rng, b=b, r=r, k=k)
    out["mask_logits"] = (rng.randn(b, r, s, s, k) * 3).astype(np.float32)
    return out, im_info


class TestDeviceMaskSelection:
    """ISSUE 14: the fused postprocess gathers each survivor's S×S grid
    for its predicted class on device; the host only applies sigmoid +
    paste + RLE.  The bar is BIT parity with the reference host chain
    (im_detect → threshold → NMS → cap), not approximate equality."""

    def _cfg(self, max_per_image=10):
        cfg = generate_config("resnet50", "PascalVOC")
        return cfg.replace(
            TEST=dataclasses.replace(cfg.TEST, MAX_PER_IMAGE=max_per_image)
        )

    def _run_device(self, cfg, out, im_info, orig_hw, k, max_out=32):
        fn = make_test_postprocess(cfg, k, 0.05, max_out=max_out)
        return {
            kk: np.asarray(v)
            for kk, v in fn(
                {kk: jnp.asarray(v) for kk, v in out.items()},
                jnp.asarray(im_info), jnp.asarray(orig_hw),
            ).items()
        }

    @pytest.mark.parametrize("seed", [0, 1, 2])
    def test_mask_probs_bitwise_and_rles_byte_identical(self, seed):
        from mx_rcnn_tpu.eval.segm import rles_for_detections
        from mx_rcnn_tpu.serve.runner import (
            cap_detections, detections_from_output,
        )

        cfg = self._cfg()
        k = 5
        rng = np.random.RandomState(seed)
        out, im_info = _fake_mask_outputs(rng, k=k)
        orig_hw = np.stack(
            [np.floor(im_info[:, 0] / im_info[:, 2]),
             np.floor(im_info[:, 1] / im_info[:, 2])], axis=1
        ).astype(np.float32)
        dev = self._run_device(cfg, out, im_info, orig_hw, k)
        assert dev["det_masks"].shape[1] == cfg.TEST.MAX_PER_IMAGE
        assert dev["det_masks"].dtype == np.float32

        for b in range(out["rois"].shape[0]):
            h, w = int(orig_hw[b][0]), int(orig_hw[b][1])
            d_dets, d_masks = detections_from_output(
                dev, im_info[b], tuple(orig_hw[b]), cfg, k, index=b
            )
            d_dets, d_masks = cap_detections(
                d_dets, cfg.TEST.MAX_PER_IMAGE, d_masks
            )
            r_dets, r_masks = detections_from_output(
                out, im_info[b], tuple(orig_hw[b]), cfg, k, index=b
            )
            r_dets, r_masks = cap_detections(
                r_dets, cfg.TEST.MAX_PER_IMAGE, r_masks
            )
            assert sum(len(d) for d in r_dets[1:]) > 0
            for j in range(1, k):
                assert len(d_dets[j]) == len(r_dets[j]), f"img {b} cls {j}"
                if len(d_dets[j]) == 0:
                    continue
                # scores and mask probabilities are pure gathers +
                # the identical numpy sigmoid: bitwise equal
                assert d_dets[j][:, 4].tobytes() == r_dets[j][:, 4].tobytes()
                assert d_masks[j].tobytes() == r_masks[j].tobytes(), (
                    f"img {b} cls {j}: device-selected mask grids differ "
                    f"from the host-path grids"
                )
                # boxes carry the XLA-vs-numpy decode ulp only
                np.testing.assert_allclose(
                    d_dets[j][:, :4], r_dets[j][:, :4], atol=1e-4
                )
                d_rles = rles_for_detections(d_masks[j], d_dets[j], h, w)
                r_rles = rles_for_detections(r_masks[j], r_dets[j], h, w)
                assert len(d_rles) == len(r_rles)
                for ra, rb in zip(d_rles, r_rles):
                    assert ra["size"] == rb["size"]
                    assert ra["counts"] == rb["counts"], (
                        f"img {b} cls {j}: RLE bytes differ"
                    )

    def test_padding_row_invariance(self):
        """Appending invalid padding rois (a bigger bucket's R) must not
        change a single selected-mask bit."""
        cfg = self._cfg()
        k, r, pad = 5, 48, 24
        rng = np.random.RandomState(3)
        out, im_info = _fake_mask_outputs(rng, r=r, k=k)
        orig_hw = np.stack(
            [np.floor(im_info[:, 0] / im_info[:, 2]),
             np.floor(im_info[:, 1] / im_info[:, 2])], axis=1
        ).astype(np.float32)
        b = out["rois"].shape[0]
        padded = {
            "rois": np.concatenate(
                [out["rois"], np.zeros((b, pad, 4), np.float32)], axis=1
            ),
            "roi_valid": np.concatenate(
                [out["roi_valid"], np.zeros((b, pad), bool)], axis=1
            ),
            "cls_prob": np.concatenate(
                [out["cls_prob"],
                 rng.rand(b, pad, k).astype(np.float32)], axis=1
            ),
            "bbox_deltas": np.concatenate(
                [out["bbox_deltas"],
                 rng.randn(b, pad, 4 * k).astype(np.float32)], axis=1
            ),
            "mask_logits": np.concatenate(
                [out["mask_logits"],
                 rng.randn(b, pad, 14, 14, k).astype(np.float32)], axis=1
            ),
        }
        base = self._run_device(cfg, out, im_info, orig_hw, k)
        wide = self._run_device(cfg, padded, im_info, orig_hw, k)
        for key in ("det_masks", "det_mask_idx", "det_mask_valid"):
            assert base[key].tobytes() == wide[key].tobytes(), key

    def test_invalid_rows_are_inert_fill(self):
        """Past the valid survivors: idx −1, valid False, and the large-
        negative logit fill (sigmoid ≈ 0 → empty mask, no exp overflow
        if one ever leaks to the host paste)."""
        # cap above the det-grid supply: max_det clamps to (K-1)*max_out
        cfg = self._cfg(max_per_image=64)
        k = 5
        rng = np.random.RandomState(4)
        out, im_info = _fake_mask_outputs(rng, r=16, k=k)
        orig_hw = np.stack(
            [np.floor(im_info[:, 0] / im_info[:, 2]),
             np.floor(im_info[:, 1] / im_info[:, 2])], axis=1
        ).astype(np.float32)
        dev = self._run_device(cfg, out, im_info, orig_hw, k, max_out=8)
        assert dev["det_masks"].shape == (2, 32, 14, 14)
        inv = ~dev["det_mask_valid"]
        assert inv.any()
        assert (dev["det_mask_idx"][inv] == -1).all()
        assert (dev["det_masks"][inv] == -80.0).all()
        with np.errstate(over="raise"):
            probs = 1.0 / (1.0 + np.exp(-dev["det_masks"][inv]))
        assert (probs < 1e-30).all()


class TestUint8Transfer:
    def test_prepare_image_uint8_roundtrip(self):
        from mx_rcnn_tpu.data.image import prepare_image
        from mx_rcnn_tpu.models.layers import normalize_images

        cfg = generate_config("resnet50", "PascalVOC")
        rng = np.random.RandomState(0)
        im = (rng.rand(200, 300, 3) * 255).astype(np.float32)
        f32, info_a = prepare_image(
            im, 128, 256, cfg.network.PIXEL_MEANS, cfg.network.PIXEL_STDS,
            [(128, 256)],
        )
        u8, info_b = prepare_image(
            im, 128, 256, cfg.network.PIXEL_MEANS, cfg.network.PIXEL_STDS,
            [(128, 256)], uint8_out=True,
        )
        np.testing.assert_array_equal(info_a, info_b)
        assert u8.dtype == np.uint8
        info = jnp.asarray(info_a[None])
        dev = np.asarray(normalize_images(jnp.asarray(u8[None]), info, cfg))[0]
        # uint8 rounding bounds the divergence at 0.5 LSB / std
        max_err = 0.5 / min(cfg.network.PIXEL_STDS)
        assert np.abs(dev - f32).max() <= max_err + 1e-5
        # bucket padding must be exactly 0 in normalized space, like the
        # host float path (which pads AFTER normalization)
        h, w = int(info_a[0]), int(info_a[1])
        assert (dev[h:] == 0).all() and (dev[:, w:] == 0).all()

    def test_float_batches_pass_through(self):
        from mx_rcnn_tpu.models.layers import normalize_images

        cfg = generate_config("resnet50", "PascalVOC")
        x = jnp.ones((1, 4, 4, 3), jnp.float32) * 0.5
        info = jnp.asarray([[4.0, 4.0, 1.0]])
        assert normalize_images(x, info, cfg) is x

    def test_testloader_emits_uint8(self):
        from mx_rcnn_tpu.data.loader import TestLoader
        from mx_rcnn_tpu.data.synthetic import SyntheticDataset

        cfg = generate_config("resnet50", "PascalVOC")
        cfg = cfg.replace(
            SHAPE_BUCKETS=((128, 128),),
            dataset=dataclasses.replace(
                cfg.dataset, NUM_CLASSES=4, SCALES=((128, 128),)
            ),
        )
        ds = SyntheticDataset(num_images=1, num_classes=4, image_size=(128, 128))
        _, batch = next(iter(TestLoader(ds.gt_roidb(), cfg)))
        assert batch["images"].dtype == np.uint8

        cfg_off = cfg.replace(
            TEST=dataclasses.replace(cfg.TEST, UINT8_TRANSFER=False)
        )
        _, batch = next(iter(TestLoader(ds.gt_roidb(), cfg_off)))
        assert batch["images"].dtype == np.float32
