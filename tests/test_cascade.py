"""Chaos matrix for the confidence-gated cascade (ISSUE 18), CPU-only
and fast.

Same philosophy as ``tests/test_rollout.py``: every test drives the
REAL ``ServingEngine`` / ``CascadeRouter`` / ``ModelRegistry`` /
``ResponseCache`` machinery and only the predict path is a numpy stub
(:class:`CascadeStub`) whose "detections" are a pure deterministic
function of the batch pixels, the family, and the serving version's
``w`` — so which family/version produced a response is visible in
every coordinate byte.  First-pass confidence is steered by the image
fill: an "easy" image scores 0.9 on the cheap family (ships), a
"hard" one 0.2 (escalates), and the flagship always scores 0.95.

The invariants under test are the ISSUE 18 acceptance criteria: the
gate is deterministic and pure-host; escalation preserves the
request's lane/tenant/deadline identity; the response cache never
crosses (family, precision, arm) keys; 100% escalation is
byte-identical to flagship-only serving; and the cascade composes
with the rest of the serve stack's chaos — poison-mixed traffic,
hot-swaps of the cheap family, and an active flagship rollout split.
Every test runs with the lock-order checker armed (graftlint R4's
runtime counterpart).
"""

import time

import numpy as np
import pytest

from mx_rcnn_tpu.core.checkpoint import save_checkpoint
from mx_rcnn_tpu.serve.batcher import Request
from mx_rcnn_tpu.serve.buckets import BucketLadder, CompileCache
from mx_rcnn_tpu.serve.cascade import (
    CascadePolicy,
    CascadeRouter,
    detection_stats,
    parse_cascade_spec,
)
from mx_rcnn_tpu.serve.engine import ServingEngine
from mx_rcnn_tpu.serve.quarantine import (
    InvalidRequest,
    QuarantineTable,
    RetriesExhausted,
    request_digest,
)
from mx_rcnn_tpu.serve.registry import ModelRegistry, UnknownModel, UnknownVersion
from mx_rcnn_tpu.serve.respcache import ResponseCache
from mx_rcnn_tpu.serve.rollout import RolloutPolicy, assign_arm


@pytest.fixture(autouse=True)
def _lock_order_check(monkeypatch):
    from mx_rcnn_tpu.analysis import lockcheck

    monkeypatch.setenv("MX_RCNN_LOCK_CHECK", "1")
    lockcheck.reset()
    yield


LADDER = ((32, 32),)

# image fills steering the stub's confidence (canvas sums at 24x24):
# easy ~173 -> cheap scores 0.9, hard ~8640 -> cheap scores 0.2,
# poison ~51840 -> the predict itself raises (query of death)
HARD_SUM = 1000.0
POISON_SUM = 20000.0


def fill_image(value: float, size=(24, 24)) -> np.ndarray:
    return np.full((*size, 3), value, np.float32)


def easy_image(i: int = 0) -> np.ndarray:
    im = fill_image(0.1)
    im[0, 0, 0] = 0.1 + i * 1e-3  # unique content, still easy
    return im


def hard_image(i: int = 0) -> np.ndarray:
    im = fill_image(5.0)
    im[0, 0, 0] = 5.0 + i * 1e-3
    return im


def params_tree(w: float):
    return {"w": np.array([w], np.float32)}


class CascadeStub:
    """Registry-backed runner stub for the cascade matrix.

    Detections are ``[None, box]`` with box x-corner
    ``1 + 50*(family is flagship) + (w - 1) * 10`` — family AND serving
    version visible in the bytes — and a score that is a pure function
    of (family, image hardness).  ``run_version`` serves a staged tree
    without touching the live slot (the rollout candidate-arm path) and
    a poison-fill slot raises from ``run`` itself (the containment
    path)."""

    def __init__(self, registry):
        self.registry = registry
        self.default_model = registry.default_model
        self.ladder = BucketLadder(LADDER)
        self.max_batch = 1
        self.cfg = None
        self.compile_cache = CompileCache()
        self.calls = {}
        self._staged = {}

    def warmup(self) -> int:
        return 0

    def make_request(self, im, deadline=None, model=None) -> Request:
        h, w = im.shape[:2]
        bh, bw = self.ladder.select(h, w)
        canvas = np.zeros((bh, bw, 3), np.float32)
        canvas[:h, :w] = im
        return Request(
            image=canvas,
            im_info=np.array([h, w, 1.0], np.float32),
            orig_hw=(h, w),
            bucket=(bh, bw),
            deadline=deadline,
            model=model,
        )

    def assemble(self, requests):
        return {"images": np.stack([r.image for r in requests])}

    def _predict(self, batch, mid, w):
        sums = batch["images"].astype(np.float64).sum(axis=(1, 2, 3))
        if float(sums.max()) > POISON_SUM:
            raise RuntimeError("injected poison predict failure")
        self.calls[mid] = self.calls.get(mid, 0) + 1
        self.compile_cache.record((mid, batch["images"].shape, "f32"))
        return {"sums": sums, "mid": mid, "w": w}

    def run(self, batch, model=None):
        mid = model or self.default_model
        w = float(np.asarray(self.registry.live(mid).params["w"]).ravel()[0])
        return self._predict(batch, mid, w)

    def run_version(self, batch, model=None, version=None):
        mid = model or self.default_model
        live = self.registry.live(mid)
        if version is None or int(version) == live.version:
            return self.run(batch, model=mid)
        staged = self._staged.get((mid, int(version)))
        if staged is None:
            raise UnknownVersion(
                f"model {mid!r} v{int(version)} is neither live nor staged"
            )
        w = float(np.asarray(staged["w"]).ravel()[0])
        return self._predict(batch, mid, w)

    def detections_for(self, out, batch, index, orig_hw=None, thresh=None,
                       model=None):
        mid = out["mid"]
        hard = float(out["sums"][index]) > HARD_SUM
        score = 0.95 if mid == "flag" else (0.2 if hard else 0.9)
        x = 1.0 + (50.0 if mid == "flag" else 0.0) + (out["w"] - 1.0) * 10.0
        return [
            None,
            np.array([[x, 2.0, x + 10.0, 12.0, score]], np.float32),
        ]

    # ---- swap / rollout target surface
    def warm_version(self, model, version, params, buckets=None, abort=None):
        self._staged[(model, int(version))] = params
        return 1

    def canary(self, model=None):
        return 1

    def discard_version(self, model, version):
        self._staged.pop((model, int(version)), None)


def make_registry(w_cheap: float = 1.0, w_flag: float = 1.0):
    reg = ModelRegistry()
    reg.register("cheap", model=None, cfg=None, params=params_tree(w_cheap))
    reg.register("flag", model=None, cfg=None, params=params_tree(w_flag))
    return reg


def make_engine(reg=None, cache=None, **kw):
    reg = reg if reg is not None else make_registry()
    runner = CascadeStub(reg)
    eng = ServingEngine(runner, max_linger=0.0, response_cache=cache, **kw)
    return eng, runner


def served_x(dets) -> float:
    """The box x-corner: which (family, version) produced these bytes."""
    return float(dets[1][0, 0])


POLICY = {"cheap": "cheap", "flagship": "flag", "min_score": 0.5}


# ---------------------------------------------------------- policy + gate

class TestPolicyAndGate:
    def test_policy_validation(self):
        with pytest.raises(ValueError, match="must differ"):
            CascadePolicy(cheap="m", flagship="m")
        with pytest.raises(ValueError, match="both"):
            CascadePolicy(cheap="", flagship="m")
        with pytest.raises(ValueError, match="min_dets"):
            CascadePolicy(cheap="a", flagship="b", min_dets=-1)

    def test_spec_parsing(self):
        p = parse_cascade_spec("small>big")
        assert (p.cheap, p.flagship, p.min_score) == ("small", "big", 0.5)
        p = parse_cascade_spec("c4_small>flagship:0.65")
        assert (p.cheap, p.flagship, p.min_score) == (
            "c4_small", "flagship", 0.65,
        )
        with pytest.raises(ValueError, match="CHEAP>FLAGSHIP"):
            parse_cascade_spec("no-arrow")

    def test_detection_stats_over_clsdets_shapes(self):
        assert detection_stats(None) == (0, 0.0)
        assert detection_stats([None, np.zeros((0, 5))]) == (0, 0.0)
        dets = [
            None,
            np.array([[0, 0, 1, 1, 0.3], [0, 0, 1, 1, 0.8]], np.float32),
            np.array([[0, 0, 1, 1, 0.6]], np.float32),
        ]
        n, mx = detection_stats(dets)
        assert n == 3 and mx == pytest.approx(0.8)

    def test_gate_deterministic_and_counted(self):
        r = CascadeRouter(CascadePolicy(**POLICY))
        dets = [None, np.array([[0, 0, 1, 1, 0.9]], np.float32)]
        assert all(r.sufficient(dets) for _ in range(3))
        assert not r.sufficient([None, np.zeros((0, 5), np.float32)])
        snap = r.snapshot()
        assert snap["first_pass"] == 4
        assert snap["first_pass_sufficient"] == 3
        assert snap["escalations"] == 1
        assert snap["escalation_rate"] == 0.25

    def test_min_dets_requires_confidently_nonempty(self):
        r = CascadeRouter(CascadePolicy(cheap="a", flagship="b",
                                        min_score=0.0, min_dets=1))
        assert not r.sufficient([None])  # empty pass must escalate
        assert r.sufficient([None, np.array([[0, 0, 1, 1, 0.1]], np.float32)])


# ------------------------------------------------------- engine routing

class TestEngineCascade:
    def test_attach_rejects_unregistered_family(self):
        eng, _ = make_engine()
        with pytest.raises(UnknownModel, match="ghost"):
            eng.attach_cascade({"cheap": "ghost", "flagship": "flag"})

    def test_easy_ships_cheap_hard_escalates(self):
        eng, runner = make_engine()
        with eng:
            eng.attach_cascade(POLICY)
            assert served_x(eng.submit(easy_image(), model="flag").result(5)) \
                == 1.0
            assert served_x(eng.submit(hard_image(), model="flag").result(5)) \
                == 51.0
            snap = eng.snapshot()
        assert snap["cascade"]["first_pass"] == 2
        assert snap["cascade"]["first_pass_sufficient"] == 1
        assert snap["cascade"]["escalations"] == 1
        assert snap["requests"]["escalations"] == 1
        assert snap["requests"]["first_pass_sufficient"] == 1
        # the escalated request ran BOTH families; the easy one only cheap
        assert runner.calls == {"cheap": 2, "flag": 1}
        # e2e accounting spans both passes as ONE completed request each
        assert snap["requests"]["completed"] == 2
        assert snap["requests"]["submitted"] == 2

    def test_direct_cheap_and_other_traffic_bypass_gate(self):
        eng, _ = make_engine()
        with eng:
            eng.attach_cascade(POLICY)
            d = eng.submit(hard_image(), model="cheap").result(5)
            assert d[1][0, 4] == np.float32(0.2)  # uncertain bytes SHIP
            snap = eng.snapshot()
        assert snap["cascade"]["first_pass"] == 0

    def test_escalation_keeps_lane_and_tenant_accounting(self):
        eng, _ = make_engine()
        with eng:
            eng.attach_cascade(POLICY)
            f = eng.submit(hard_image(), model="flag", lane="interactive")
            assert served_x(f.result(5)) == 51.0
            lanes = eng.snapshot()["lanes"]
        # both passes rode the ORIGINAL flagship lane — nothing in bulk
        assert lanes["interactive"]["completed"] == 1
        assert lanes.get("bulk", {}).get("completed", 0) == 0

    def test_full_escalation_byte_identical_to_flagship_only(self):
        imgs = [easy_image(1), hard_image(1), fill_image(2.0)]
        eng, _ = make_engine()
        with eng:
            eng.attach_cascade(dict(POLICY, min_score=1.01))
            casc = [eng.submit(im, model="flag").result(5)[1].tobytes()
                    for im in imgs]
            snap = eng.snapshot()["cascade"]
        assert snap["escalation_rate"] == 1.0
        eng2, _ = make_engine()
        with eng2:
            base = [eng2.submit(im, model="flag").result(5)[1].tobytes()
                    for im in imgs]
        assert casc == base

    def test_zero_threshold_never_escalates(self):
        eng, runner = make_engine()
        with eng:
            eng.attach_cascade(dict(POLICY, min_score=0.0))
            for i in range(3):
                assert served_x(
                    eng.submit(hard_image(i), model="flag").result(5)
                ) == 1.0
            snap = eng.snapshot()["cascade"]
        assert snap["escalations"] == 0
        assert snap["first_pass_sufficient"] == 3
        assert runner.calls == {"cheap": 3}


# ------------------------------------------------- response-cache keying

class TestCascadeCacheKeys:
    def test_keys_never_cross_families_and_flagship_probe_hits(self):
        cache = ResponseCache()
        eng, runner = make_engine(cache=cache)
        with eng:
            eng.attach_cascade(POLICY)
            d_easy = eng.submit(easy_image(), model="flag").result(5)
            d_hard = eng.submit(hard_image(), model="flag").result(5)
            # each digest lives under exactly ONE family key — the gate
            # is deterministic per (policy, cheap version, image)
            fams = {}
            for k in list(cache._entries):
                fams.setdefault(k[3], set()).add(k[0])
            assert all(len(v) == 1 for v in fams.values())
            assert {k[0] for k in cache._entries} == {"cheap", "flag"}
            # a resubmitted escalated digest hits the FLAGSHIP key at
            # submit — no cheap pass, no gate, no device trip at all
            calls0 = dict(runner.calls)
            first0 = eng.snapshot()["cascade"]["first_pass"]
            d_hit = eng.submit(hard_image(), model="flag").result(5)
            assert d_hit[1].tobytes() == d_hard[1].tobytes()
            assert runner.calls == calls0
            assert eng.snapshot()["cascade"]["first_pass"] == first0
            # and a resubmitted easy digest hits the cheap key
            assert eng.submit(easy_image(), model="flag").result(5)[1] \
                .tobytes() == d_easy[1].tobytes()
        assert cache.snapshot()["hits"] == 2

    def test_uncertain_first_pass_is_never_cached(self):
        cache = ResponseCache()
        eng, _ = make_engine(cache=cache)
        with eng:
            eng.attach_cascade(POLICY)
            eng.submit(hard_image(7), model="flag").result(5)
        # only the flagship (final-serving) entry exists — the cheap
        # pass's uncertain bytes never seeded the cache
        keys = list(cache._entries)
        assert len(keys) == 1 and keys[0][0] == "flag"


# --------------------------------------------------------- chaos rows

class TestCascadeChaos:
    def test_escalation_correct_under_poison_mix(self):
        """A query-of-death mixed into cascade traffic fails ITSELF
        (typed, after its retry budget) while easy/hard neighbours keep
        routing correctly — and malformed input never reaches the
        batcher at all."""
        reg = make_registry()
        runner = CascadeStub(reg)
        runner.quarantine = QuarantineTable(k=2, ttl_s=60.0)
        eng = ServingEngine(runner, max_linger=0.0, retry_budget=2)
        with eng:
            eng.attach_cascade(POLICY)
            with pytest.raises(InvalidRequest):
                eng.submit(np.full((8, 8, 3), np.nan, np.float32),
                           model="flag")
            f_poison = eng.submit(fill_image(30.0), model="flag")
            f_easy = eng.submit(easy_image(), model="flag")
            f_hard = eng.submit(hard_image(), model="flag")
            assert served_x(f_easy.result(10)) == 1.0
            assert served_x(f_hard.result(10)) == 51.0
            with pytest.raises(RetriesExhausted):
                f_poison.result(10)
            snap = eng.snapshot()
        assert snap["cascade"]["escalations"] == 1
        assert snap["cascade"]["first_pass_sufficient"] == 1
        assert snap["requests"]["invalid"] == 1
        assert snap["requests"]["exhausted"] == 1
        assert snap["requests"]["completed"] == 2

    def test_cascade_with_cheap_family_hot_swap(self, tmp_path):
        """A live hot-swap of the CHEAP family mid-cascade: new cheap
        bytes after commit, cache invalidated for the cheap family only,
        flagship escalations unaffected throughout."""
        cache = ResponseCache()
        eng, _ = make_engine(cache=cache)
        ckpt = save_checkpoint(
            str(tmp_path / "cheap-v2"), {"params": params_tree(2.0)}, 1
        )
        with eng:
            eng.attach_cascade(POLICY)
            v1_easy = eng.submit(easy_image(), model="flag").result(5)
            v1_hard = eng.submit(hard_image(), model="flag").result(5)
            assert served_x(v1_easy) == 1.0
            eng.swap("cheap", ckpt, block=True)
            # cheap entries dropped, flagship entry survives
            assert {k[0] for k in cache._entries} == {"flag"}
            v2_easy = eng.submit(easy_image(), model="flag").result(5)
            v2_hard = eng.submit(hard_image(), model="flag").result(5)
        assert served_x(v2_easy) == 11.0  # w=2.0 visible in the bytes
        assert v2_hard[1].tobytes() == v1_hard[1].tobytes()
        # the fresh cheap entry is keyed by the NEW live version
        assert any(k[0] == "cheap" and k[1] == 2 for k in cache._entries)

    def test_cascade_rollout_arm_isolation(self, tmp_path):
        """An active FLAGSHIP rollout splits escalated traffic by the
        same digest-deterministic assignment as direct traffic: a
        digest's arm is stable across resubmits, candidate and
        incumbent bytes differ, and cache entries stay keyed by the
        SERVED version — arms never share bytes."""
        cache = ResponseCache()
        reg = make_registry()
        runner = CascadeStub(reg)
        eng = ServingEngine(runner, max_linger=0.0, response_cache=cache)
        ckpt = save_checkpoint(
            str(tmp_path / "flag-v2"), {"params": params_tree(1.5)}, 1
        )
        with eng:
            eng.attach_cascade(POLICY)
            ctl = eng.attach_rollout()
            ro = ctl.start("flag", ckpt, policy=RolloutPolicy(
                split_pct=50.0, shadow=False, min_compared=10_000,
                min_served=10_000, min_error_samples=10_000,
                min_latency_samples=10_000, hold_s=30.0,
                eval_interval_s=0.01,
            ))
            deadline = time.monotonic() + 10.0
            while not ctl.active("flag"):
                assert time.monotonic() < deadline, "split never opened"
                time.sleep(0.01)
            # two hard images on opposite arms (recomputed, not
            # hardcoded, so the test tracks the digest function)
            im_cand = im_inc = None
            for i in range(256):
                im = hard_image(i)
                if assign_arm(request_digest(im), 50.0):
                    im_cand = im_cand if im_cand is not None else im
                else:
                    im_inc = im_inc if im_inc is not None else im
                if im_cand is not None and im_inc is not None:
                    break
            assert im_cand is not None and im_inc is not None
            for _ in range(2):  # arm assignment stable across resubmits
                assert served_x(
                    eng.submit(im_cand, model="flag").result(5)
                ) == 56.0  # flagship candidate: 1 + 50 + (1.5-1)*10
                assert served_x(
                    eng.submit(im_inc, model="flag").result(5)
                ) == 51.0  # flagship incumbent
            snap = eng.snapshot()["cascade"]
            # 3, not 4: the incumbent digest's resubmit hit the
            # flagship cache (probed at the live version) before any
            # cheap pass; the candidate digest is keyed under the
            # candidate version, so its resubmit re-escalated — arm-
            # coherent bytes either way, asserted above
            assert snap["escalations"] == 3
            # cache: both digests under the flagship family, keyed by
            # the version that SERVED them — never each other's
            flag_keys = {k[3]: k[1] for k in cache._entries
                         if k[0] == "flag"}
            assert flag_keys[cache.digest(im_cand)] == 2
            assert flag_keys[cache.digest(im_inc)] == 1
        # engine stop cancels the in-flight rollout (the swap interlock)
        with pytest.raises(Exception):
            ro.result(0)
