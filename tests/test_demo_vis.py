"""Demo + visualization: overlay rendering and the demo_net path
(reference: ``demo.py``, ``rcnn/core/tester.py :: draw_all_detection``)."""

import dataclasses

import numpy as np
import pytest

from mx_rcnn_tpu.utils.visualize import class_color, draw_detections, save_image


class TestDrawDetections:
    def test_overlay_draws_box_pixels(self, tmp_path):
        im = np.zeros((100, 120, 3), np.uint8)
        dets = {"cat": np.array([[10, 20, 60, 80, 0.95]], np.float32)}
        out = draw_detections(im, dets, thresh=0.5)
        assert out.shape == im.shape
        color = np.array(class_color(1))
        # box edges must carry the class color (check a left-edge pixel)
        edge = out[50, 10]
        assert (edge == color).all(), f"edge pixel {edge} != {color}"
        # inside the box (away from the 2px edges and the label) stays
        # background
        assert (out[50, 35] == 0).all()

    def test_below_thresh_not_drawn(self):
        im = np.zeros((50, 50, 3), np.uint8)
        dets = {"cat": np.array([[5, 5, 40, 40, 0.3]], np.float32)}
        out = draw_detections(im, dets, thresh=0.5)
        assert (out == 0).all()

    def test_save_roundtrip(self, tmp_path):
        import cv2

        im = np.zeros((40, 40, 3), np.uint8)
        im[:, :, 0] = 200  # red in RGB
        path = str(tmp_path / "x.png")
        save_image(path, im)
        back = cv2.imread(path)  # BGR
        assert back[0, 0, 2] == 200


class TestDemoNet:
    def test_demo_on_synthetic_image(self, tmp_path):
        """demo_net end to end on a synthetic image with a tiny model:
        runs, returns only above-threshold classes, renders an overlay."""
        import jax

        from mx_rcnn_tpu.core.tester import Predictor
        from mx_rcnn_tpu.data.synthetic import SyntheticDataset, synthetic_image
        from mx_rcnn_tpu.models import FasterRCNN
        from mx_rcnn_tpu.tools.demo import demo_net
        from tests.test_alternate import tiny_alt_cfg

        cfg = tiny_alt_cfg()
        imdb = SyntheticDataset(
            num_images=1, num_classes=4, image_size=(128, 128), max_boxes=2
        )
        rec = imdb.gt_roidb()[0]
        im = synthetic_image(rec, rec["synthetic_seed"])

        model = FasterRCNN(cfg)
        params = model.init(
            {"params": jax.random.key(0)},
            np.zeros((1, 128, 128, 3), np.float32),
            np.array([[128, 128, 1.0]], np.float32),
            train=False,
        )["params"]
        predictor = Predictor(model, params)
        names = ("__background__", "a", "b", "c")
        dets = demo_net(predictor, im, cfg, names, vis_thresh=0.0)
        for name, d in dets.items():
            assert name in names[1:]
            assert d.shape[1] == 5
        overlay = draw_detections(im, dets, 0.0)
        save_image(str(tmp_path / "demo.png"), overlay)
        assert (tmp_path / "demo.png").exists()
