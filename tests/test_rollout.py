"""Chaos matrix for progressive rollout (ISSUE 17), CPU-only and fast.

Same philosophy as ``tests/test_registry.py``: every test drives the
REAL ``RolloutController`` / ``ModelRegistry`` / engine machinery —
including real orbax checkpoints through the manifest + structure
gates — and only the predict path is a numpy stub
(:class:`FakeRolloutRunner`) whose "detections" are a pure
deterministic function of the batch pixels AND the serving version's
``w``, emitted in the serve stack's per-class ClsDets shape so
``detection_parity`` sees real boxes.  A version's ``w`` shifts its
box corners by ``(w - 1) * 10`` px: ``w = 1.0001`` is a faithful
candidate (0.001 px drift — promotes), ``w = 2.0`` is a divergent one
(10 px shift, IoU 0.14 — every shadow comparison reports unmatched
detections and the rollout must auto-roll-back).

The invariants under test are the ISSUE 17 acceptance criteria:
deterministic digest-hash arm assignment (same digest → same arm,
always — and the response cache never crosses arms); shadow scoring
never blocks or degrades the live SLO path; a divergence-injected
candidate is auto-rolled-back while the incumbent serves
byte-identical responses throughout (live pointer untouched); a
promote under live load loses zero requests and adds zero compile
misses; and distilled records round-trip the synthetic-record schema
through the real training loader.
"""

import threading
import time

import numpy as np
import pytest

from mx_rcnn_tpu.core.checkpoint import save_checkpoint
from mx_rcnn_tpu.serve.batcher import Request
from mx_rcnn_tpu.serve.buckets import BucketLadder, CompileCache
from mx_rcnn_tpu.serve.engine import ServingEngine
from mx_rcnn_tpu.serve.loadgen import run_load, synthetic_image
from mx_rcnn_tpu.serve.quarantine import request_digest
from mx_rcnn_tpu.serve.registry import (
    ModelRegistry,
    TRANSITION_LOG_MAX,
    UnknownVersion,
    VersionState,
)
from mx_rcnn_tpu.serve.respcache import ResponseCache
from mx_rcnn_tpu.serve.rollout import (
    RolloutAborted,
    RolloutCancelled,
    RolloutController,
    RolloutInProgress,
    RolloutPolicy,
    assign_arm,
)
from mx_rcnn_tpu.utils import faults


@pytest.fixture(autouse=True)
def _lock_order_check(monkeypatch):
    """Whole matrix under MX_RCNN_LOCK_CHECK=1: every serve-stack lock
    becomes an order-asserting proxy that raises LockOrderViolation at
    the acquire that would close a cycle — the controller lock, the
    shadow condition, and the divergence-report leaf included."""
    from mx_rcnn_tpu.analysis import lockcheck

    monkeypatch.setenv("MX_RCNN_LOCK_CHECK", "1")
    lockcheck.reset()
    yield


@pytest.fixture(autouse=True)
def _no_faults(monkeypatch):
    monkeypatch.delenv(faults.ENV_VAR, raising=False)
    faults.reset()
    yield
    faults.reset()


LADDER = ((32, 32), (48, 64))
SIZES = ((24, 24), (32, 48), (16, 16))

# checkpoints store params as float32 — expectations must use the same
# rounded value or the "byte-identical" comparisons drift by one ULP
W_GOOD = float(np.float32(1.0001))
W_BAD = 2.0


def params_tree(w: float):
    return {"w": np.array([w], np.float32)}


def cls_dets(pixel_sum: float, w: float):
    """The fake's "detections" for one slot: a single confident box
    whose position is a pure function of the slot pixels and the
    serving version's ``w`` — a version change is visible in every
    coordinate byte, and ``(w - 1) * 10`` px of injected drift."""
    x = float(pixel_sum) % 7.0
    shift = (w - 1.0) * 10.0
    box = np.array(
        [[5.0 + x + shift, 6.0 + x + shift,
          25.0 + x + shift, 26.0 + x + shift, 0.9]],
        np.float32,
    )
    return [None, box]


class FakeRolloutRunner:
    """Registry-backed runner stub implementing the full rollout target
    surface (``warm_version`` / ``run_version`` / ``discard_version`` /
    ``assemble`` / ``detections_for``) with the real sync semantics:
    predict resolves the registry's live pointer per batch, and a
    version-pinned predict serves the STAGED tree without touching the
    live slot (the zero-recompile split path)."""

    def __init__(self, registry, service_s: float = 0.0,
                 warm_delay_s: float = 0.0):
        self.registry = registry
        self.default_model = registry.default_model
        self.service_s = service_s
        self.warm_delay_s = warm_delay_s
        self.ladder = BucketLadder(LADDER)
        self.max_batch = 2
        self.cfg = None
        self.compile_cache = CompileCache()
        self.served_buckets = {}
        self.warm_started = threading.Event()
        self._versions = {}
        self._params = {}
        self._staged = {}
        self._lock = threading.Lock()

    def _mid(self, model):
        return self.default_model if model is None else model

    def _sync(self, mid):
        live = self.registry.live(mid)
        with self._lock:
            if self._versions.get(mid) == live.version:
                return
            staged = self._staged.pop((mid, live.version), None)
            for k in [k for k in self._staged if k[0] == mid]:
                self._staged.pop(k, None)
            self._params[mid] = (
                staged if staged is not None else live.params
            )
            self._versions[mid] = live.version

    # ---- runner facade
    def warmup(self, buckets=None, models=None) -> int:
        for m in (models or self.registry.model_ids()):
            self._sync(m)
            for bh, bw in (buckets or self.ladder):
                self.compile_cache.record((m, (self.max_batch, bh, bw, 3),
                                           "f32"))
        return self.compile_cache.misses

    def make_request(self, im, deadline=None, model=None) -> Request:
        h, w = im.shape[:2]
        bh, bw = self.ladder.select(h, w)
        canvas = np.zeros((bh, bw, 3), np.float32)
        canvas[:h, :w] = im
        return Request(
            image=canvas,
            im_info=np.array([h, w, 1.0], np.float32),
            orig_hw=(h, w),
            bucket=(bh, bw),
            deadline=deadline,
            model=model,
        )

    def assemble(self, requests):
        images = [r.image for r in requests]
        while len(images) < self.max_batch:
            images.append(images[0])
        return {
            "images": np.stack(images),
            "im_info": np.stack(
                [r.im_info for r in requests]
                + [requests[0].im_info] * (self.max_batch - len(requests))
            ),
        }

    def _predict(self, batch, mid, w):
        if self.service_s:
            time.sleep(self.service_s)
        self.compile_cache.record((mid, batch["images"].shape, "f32"))
        self.served_buckets.setdefault(mid, set()).add(
            tuple(batch["images"].shape[1:3])
        )
        return {
            "sums": batch["images"].astype(np.float64).sum(axis=(1, 2, 3)),
            "w": w,
        }

    def run(self, batch, model=None):
        mid = self._mid(model)
        self._sync(mid)
        w = float(np.asarray(self._params[mid]["w"]).ravel()[0])
        return self._predict(batch, mid, w)

    def run_version(self, batch, model=None, version=None):
        mid = self._mid(model)
        self._sync(mid)
        with self._lock:
            live_v = self._versions.get(mid)
            staged = self._staged.get((mid, int(version)))  \
                if version is not None else None
        if version is None or int(version) == live_v:
            return self.run(batch, model=model)
        if staged is None:
            raise UnknownVersion(
                f"model {mid!r} v{int(version)} is neither live "
                f"(v{live_v}) nor staged"
            )
        w = float(np.asarray(staged["w"]).ravel()[0])
        return self._predict(batch, mid, w)

    def detections_for(self, out, batch, index, orig_hw=None, thresh=None,
                       model=None):
        return cls_dets(out["sums"][index], out["w"])

    # ---- rollout target surface
    def warm_version(self, model, version, params, buckets=None, abort=None):
        mid = self._mid(model)
        self.warm_started.set()
        if abort is not None:
            abort()
        if buckets is None:
            buckets = sorted(self.served_buckets.get(mid, ())) or list(
                self.ladder
            )
        for _ in buckets:
            if abort is not None:
                abort()
            if self.warm_delay_s:
                time.sleep(self.warm_delay_s)
        with self._lock:
            self._staged[(mid, int(version))] = params
        return len(buckets)

    def canary(self, model=None):
        return 1

    def discard_version(self, model, version):
        with self._lock:
            self._staged.pop((self._mid(model), int(version)), None)


def make_registry(w: float = 1.0):
    reg = ModelRegistry()
    reg.register("det", model=None, cfg=None, params=params_tree(w))
    return reg


def expected_bytes(im: np.ndarray, w: float) -> bytes:
    """The confident box the engine resolves for ``im`` under version
    ``w`` — the single computation shared by the fake and the tests."""
    bh, bw = BucketLadder(LADDER).select(*im.shape[:2])
    canvas = np.zeros((bh, bw, 3), np.float32)
    canvas[: im.shape[0], : im.shape[1]] = im
    s = canvas.astype(np.float64).sum()
    return cls_dets(s, w)[1].tobytes()


def wait_for(pred, timeout=10.0, msg="condition"):
    t_end = time.monotonic() + timeout
    while time.monotonic() < t_end:
        if pred():
            return
        time.sleep(0.01)
    raise AssertionError(f"timed out waiting for {msg}")


@pytest.fixture(scope="module")
def ckpts(tmp_path_factory):
    """Committed orbax dumps with the registry tree shape: ``good`` is
    a faithful candidate (0.001 px drift), ``bad`` a divergent one
    (10 px shift — trips the unmatched bound on every comparison)."""
    root = tmp_path_factory.mktemp("rollout-ckpts")
    out = {}
    for name, w in (("good", W_GOOD), ("bad", W_BAD)):
        out[name] = save_checkpoint(
            str(root / name), {"params": params_tree(w)}, 1
        )
    return out


def fast_policy(**over):
    base = dict(
        split_pct=30.0, shadow=True, min_compared=4, min_served=3,
        min_error_samples=10_000, min_latency_samples=10_000,
        hold_s=0.05, eval_interval_s=0.01, score_thresh=0.1,
    )
    base.update(over)
    return RolloutPolicy(**base)


def find_arm_images(pct=50.0, size=(24, 24)):
    """Two concrete images whose content digests deterministically land
    on opposite arms at ``pct`` — recomputed, not hardcoded, so the
    test tracks the digest function."""
    cand = inc = None
    for i in range(256):
        im = np.full((*size, 3), float(i % 97) + 0.5, np.float32)
        im[0, 0, 0] = i  # unique content
        if assign_arm(request_digest(im), pct):
            cand = cand if cand is not None else im
        else:
            inc = inc if inc is not None else im
        if cand is not None and inc is not None:
            return cand, inc
    raise AssertionError("digest space did not cover both arms")


# ------------------------------------------------- deterministic split

def test_assign_arm_deterministic_and_proportional():
    digests = [request_digest(synthetic_image(i, 16, 16, 3))
               for i in range(400)]
    for d in digests[:32]:
        assert assign_arm(d, 25.0) == assign_arm(d, 25.0)
        assert assign_arm(d, 0.0) is False
        assert assign_arm(d, 100.0) is True
    frac = sum(assign_arm(d, 25.0) for d in digests) / len(digests)
    assert 0.15 < frac < 0.35, frac
    # monotone: an arm won at pct stays won at any higher pct
    for d in digests[:64]:
        if assign_arm(d, 10.0):
            assert assign_arm(d, 60.0)


def test_engine_split_same_digest_same_arm(ckpts):
    """Engine-level determinism with NO cache in the loop: the same
    image resubmitted under an active split serves the same arm's bytes
    every time, and the two arms' bytes differ."""
    reg = make_registry()
    runner = FakeRolloutRunner(reg)
    eng = ServingEngine(runner, max_linger=0.0).start()
    try:
        ctl = eng.attach_rollout()
        ro = ctl.start("det", ckpts["bad"], policy=fast_policy(
            split_pct=50.0, shadow=False, min_served=10_000, hold_s=30.0,
        ))
        wait_for(lambda: ctl.active("det"), msg="split open")
        im_cand, im_inc = find_arm_images(50.0)
        for _ in range(3):
            got = eng.submit(im_cand).result(5)[1].tobytes()
            assert got == expected_bytes(im_cand, 2.0)
            got = eng.submit(im_inc).result(5)[1].tobytes()
            assert got == expected_bytes(im_inc, 1.0)
        snap = eng.snapshot()["rollout"]["models"]["det"]
        assert snap["served"]["candidate"] == 3
        assert snap["served"]["incumbent"] == 3
        assert not ro.done()
    finally:
        eng.stop()
    with pytest.raises(RolloutCancelled):
        ro.result(0)


# --------------------------------------------- satellite 1: cache arms

def test_response_cache_never_crosses_arms(ckpts):
    """The regression the split demands of the response cache: a key is
    minted against the SERVED arm's version, so a repeated request hits
    only its own arm's bytes — never arm-A bytes for an arm-B digest —
    and a rollback drops the candidate's entries."""
    reg = make_registry()
    runner = FakeRolloutRunner(reg)
    cache = ResponseCache(capacity=64)
    eng = ServingEngine(runner, max_linger=0.0, response_cache=cache).start()
    try:
        ctl = eng.attach_rollout()
        ro = ctl.start("det", ckpts["bad"], policy=fast_policy(
            split_pct=50.0, shadow=False, min_served=10_000, hold_s=30.0,
        ))
        wait_for(lambda: ctl.active("det"), msg="split open")
        im_cand, im_inc = find_arm_images(50.0)
        v_cand = reg.entry("det").versions[-1].version
        cand_bytes = expected_bytes(im_cand, 2.0)
        inc_bytes = expected_bytes(im_inc, 1.0)
        # miss then hit, per arm — hits must reproduce the ARM's bytes
        for _ in range(2):
            assert eng.submit(im_cand).result(5)[1].tobytes() == cand_bytes
            assert eng.submit(im_inc).result(5)[1].tobytes() == inc_bytes
        assert cache.hits == 2
        # the two arms hold disjoint keys: same model, different version
        keys = list(cache._entries)
        assert {k[1] for k in keys} == {1, v_cand}
        # cancel → rollback path invalidates the model's entries; the
        # same candidate-arm digest now recomputes on the incumbent
        ctl.stop()
        with pytest.raises(RolloutCancelled):
            ro.result(0)
        assert eng.submit(im_cand).result(5)[1].tobytes() == \
            expected_bytes(im_cand, 1.0)
    finally:
        eng.stop()


# ------------------------------------------------ shadow off the SLO path

def test_shadow_never_blocks_slo_and_promotes_on_evidence(ckpts):
    """Pure shadow (split 0%): every live request resolves through the
    incumbent with incumbent bytes; the candidate earns promotion
    entirely from mirrored comparisons that never touch the batcher,
    the submit gate, or any tenant budget."""
    reg = make_registry()
    runner = FakeRolloutRunner(reg)
    eng = ServingEngine(runner, max_linger=0.0).start()
    try:
        ctl = eng.attach_rollout()
        ro = ctl.start("det", ckpts["good"], policy=fast_policy(
            split_pct=0.0, min_compared=6,
        ))
        wait_for(lambda: ro.state == "evaluating" or ro.done(),
                 msg="shadow open")
        n = 0
        deadline = time.monotonic() + 20
        while not ro.done() and time.monotonic() < deadline:
            im = synthetic_image(n, *SIZES[n % len(SIZES)], 3)
            got = eng.submit(im).result(5)[1].tobytes()
            # every live response is the incumbent's, byte-identical —
            # shadow scoring is invisible to callers
            assert got in (expected_bytes(im, 1.0),
                           expected_bytes(im, W_GOOD))
            n += 1
        result = ro.result(5)
        assert result["version"] == 2 and result["previous"] == 1
        div = result["divergence"]
        assert div["compared"] >= 6 and div["failed"] == 0
        assert div["mirrored"] >= div["compared"]
        assert div["max_box_delta_px"] <= 0.01
        snap = eng.snapshot()
        # the shadow lane never entered the engine: submissions are
        # exactly the live requests, none failed, none expired
        assert snap["requests"]["submitted"] == n
        assert snap["requests"]["failed"] == 0
        assert snap["rollout"]["promoted"] == 1
        assert reg.live("det").version == 2
    finally:
        eng.stop()


# ------------------------------------------------- divergence rollback

def test_divergence_rollback_serves_byte_identical_incumbent(ckpts):
    """The headline guarantee: a divergent candidate is auto-rolled-back
    by the evaluator while every response — during the rollout, at the
    rollback instant, and after — carries the incumbent's exact bytes.
    The live pointer never moves."""
    reg = make_registry()
    runner = FakeRolloutRunner(reg)
    eng = ServingEngine(runner, max_linger=0.0).start()
    try:
        ctl = eng.attach_rollout()
        ro = ctl.start("det", ckpts["bad"], policy=fast_policy(
            split_pct=0.0, min_compared=3, hold_s=30.0,
        ))
        wait_for(lambda: ro.state == "evaluating" or ro.done(),
                 msg="shadow open")
        n = 0
        deadline = time.monotonic() + 20
        while not ro.done() and time.monotonic() < deadline:
            im = synthetic_image(n, *SIZES[n % len(SIZES)], 3)
            got = eng.submit(im).result(5)[1].tobytes()
            assert got == expected_bytes(im, 1.0), \
                f"request {n} not incumbent bytes during rollout"
            n += 1
        with pytest.raises(RolloutAborted) as exc:
            ro.result(5)
        assert exc.value.stage == "evaluate"
        assert "unmatched" in str(exc.value.cause)
        # live pointer untouched; candidate retired + released; staged
        # device tree discarded
        assert reg.live("det").version == 1
        cand = reg.entry("det").versions[-1]
        assert cand.state is VersionState.RETIRED and cand.params is None
        assert not runner._staged
        snap = eng.snapshot()["rollout"]
        assert snap["rolled_back"] == 1 and snap["promoted"] == 0
        assert snap["models"]["det"]["state"] == "rolled_back"
        assert snap["models"]["det"]["divergence"]["max_unmatched"] >= 1
        # and the incumbent keeps serving, byte-identical
        im = synthetic_image(999, 24, 24, 3)
        assert eng.submit(im).result(5)[1].tobytes() == \
            expected_bytes(im, 1.0)
    finally:
        eng.stop()


def test_structure_mismatch_aborts_before_device(tmp_path):
    ck = save_checkpoint(
        str(tmp_path / "misshape"),
        {"params": {"w": np.zeros((2, 2), np.float32)}}, 1,
    )
    reg = make_registry()
    runner = FakeRolloutRunner(reg)
    ctl = RolloutController(reg, runner)
    with pytest.raises(RolloutAborted) as exc:
        ctl.start("det", ck, block=True, timeout=30)
    assert exc.value.stage == "verify"
    assert not runner.warm_started.is_set()
    assert reg.live("det").version == 1
    assert ctl.rolled_back == 1
    ctl.stop()


# ------------------------------------------------ promote under load

def test_promote_under_load_zero_lost_zero_recompile(ckpts):
    """A faithful candidate promotes through the atomic flip while live
    load is in flight: zero requests lost, zero failed, and the
    candidate's split traffic added ZERO compile misses (params are a
    traced jit argument — the whole rollout reuses live signatures)."""
    reg = make_registry()
    runner = FakeRolloutRunner(reg, service_s=0.002)
    eng = ServingEngine(runner, max_linger=0.001, max_queue=64).start()
    try:
        eng.attach_rollout()
        misses0 = runner.compile_cache.misses
        N = 48
        report = {}

        def load():
            report.update(run_load(
                eng, num_requests=N, concurrency=4, sizes=SIZES, seed=7,
                collect=True,
            ))

        t = threading.Thread(target=load)
        t.start()
        wait_for(lambda: eng.metrics.completed >= N // 6, msg="mid-load")
        result = eng.rollout.start(
            "det", ckpts["good"], policy=fast_policy(), block=True,
            timeout=60,
        )
        t.join()
        assert result["version"] == 2 and result["previous"] == 1
        assert result["split_served"] >= 3 and result["split_errors"] == 0
        assert report["outcomes"]["ok"] == N
        assert report["outcomes"].get("error", 0) == 0
        snap = eng.snapshot()
        assert snap["requests"]["failed"] == 0
        assert snap["rollout"]["promoted"] == 1
        assert reg.live("det").version == 2
        # zero steady-state recompiles across split + shadow + promote
        assert runner.compile_cache.misses == misses0
        # every response was one version's bytes, never a mixture
        sizes_rng = np.random.RandomState(7)
        req_sizes = [SIZES[sizes_rng.randint(len(SIZES))] for _ in range(N)]
        for i in range(N):
            kind, dets = report["_results"][i]
            assert kind == "ok", f"request {i} resolved {kind}"
            im = synthetic_image(i, *req_sizes[i], 7)
            assert dets[1].tobytes() in (
                expected_bytes(im, 1.0), expected_bytes(im, W_GOOD)
            ), f"request {i} served mixed-version bytes"
        # per-version metrics partition recorded both arms
        assert {"det:v1", "det:v2"} <= set(snap["versions"])
        # post-promote traffic is candidate bytes
        im = synthetic_image(7777, 24, 24, 3)
        assert eng.submit(im).result(5)[1].tobytes() == \
            expected_bytes(im, W_GOOD)
    finally:
        eng.stop()


# --------------------------------------------------- control-plane edges

def test_second_rollout_while_in_flight_rejected(ckpts):
    reg = make_registry()
    runner = FakeRolloutRunner(reg, warm_delay_s=0.15)
    ctl = RolloutController(reg, runner)
    ro = ctl.start("det", ckpts["good"], policy=fast_policy(hold_s=30.0))
    try:
        wait_for(runner.warm_started.is_set, msg="warm start")
        with pytest.raises(RolloutInProgress):
            ctl.start("det", ckpts["bad"])
    finally:
        ctl.stop()
    with pytest.raises(RolloutCancelled):
        ro.result(0)
    assert ctl.cancelled == 1
    assert reg.live("det").version == 1
    assert reg.entry("det").versions[-1].state is VersionState.RETIRED
    assert not runner._staged


def test_engine_stop_cancels_rollout(ckpts):
    reg = make_registry()
    runner = FakeRolloutRunner(reg, warm_delay_s=0.1)
    eng = ServingEngine(runner, max_linger=0.0).start()
    eng.attach_rollout()
    ro = eng.rollout.start("det", ckpts["good"],
                           policy=fast_policy(hold_s=30.0))
    wait_for(runner.warm_started.is_set, msg="warm start")
    eng.stop()
    assert ro.done()
    with pytest.raises(RolloutCancelled):
        ro.result(0)
    assert ro.thread is not None and not ro.thread.is_alive()
    assert reg.live("det").version == 1


def test_run_version_unknown_version_is_typed(ckpts):
    reg = make_registry()
    runner = FakeRolloutRunner(reg)
    runner.warmup()
    im = np.ones((24, 24, 3), np.float32)
    batch = runner.assemble([runner.make_request(im)])
    with pytest.raises(UnknownVersion):
        runner.run_version(batch, version=99)
    # version=None and version=live both serve the live tree
    a = runner.run_version(batch)["sums"]
    b = runner.run_version(batch, version=reg.live("det").version)["sums"]
    np.testing.assert_array_equal(a, b)


def test_admin_rollout_surface(ckpts):
    reg = make_registry()
    runner = FakeRolloutRunner(reg)
    eng = ServingEngine(runner, max_linger=0.0).start()
    try:
        eng.attach_rollout(policy=fast_policy(split_pct=0.0, min_compared=0,
                                              shadow=False))
        assert eng.admin("rollout status") == eng.rollout.snapshot()
        out = eng.admin(f"rollout det {ckpts['good']}")
        assert out["version"] == 2
        assert reg.live("det").version == 2
    finally:
        eng.stop()


# ------------------------------- satellite 2: bounded logs + quarantine

def test_transition_log_is_ring_bounded():
    reg = make_registry()
    ver = reg.live("det")
    for i in range(TRANSITION_LOG_MAX + 40):
        reg._transition(ver, VersionState.LIVE, f"tick {i}")
    assert len(ver.transitions) == TRANSITION_LOG_MAX
    snap = ver.snapshot()
    assert snap["transitions_dropped"] == 41  # register + 40 overflow
    # the ring kept the NEWEST entries
    assert snap["transitions"][-1]["reason"] == f"tick {TRANSITION_LOG_MAX + 39}"


def test_quarantine_suspects_ring_counts_drops():
    from mx_rcnn_tpu.serve.quarantine import QuarantineTable

    qt = QuarantineTable(k=10, ttl_s=300.0, max_suspects=4)
    for i in range(10):
        qt.note_trip([(f"digest-{i:04d}", None)])
    snap = qt.snapshot()
    # each trip purges down to max_suspects BEFORE adding its own, so
    # the table holds at most max_suspects + 1 and every overflow is
    # counted instead of silently forgotten
    assert len(snap["suspects"]) == 5
    assert snap["suspects_dropped"] == 5
    # the ring kept the NEWEST suspects
    assert "digest-0009"[:12] in snap["suspects"]
    assert "digest-0000"[:12] not in snap["suspects"]


# -------------------------------------- closed loop: distill round-trip

def test_distill_record_schema_roundtrips_through_loader(tmp_path):
    """Harvested records must be indistinguishable from
    ``SyntheticDataset.gt_roidb`` output: same keys, same dtypes, and
    the REAL training loader must batch them."""
    import dataclasses

    from mx_rcnn_tpu.config import generate_config
    from mx_rcnn_tpu.data.loader import TrainLoader
    from mx_rcnn_tpu.data.synthetic import SyntheticDataset
    from mx_rcnn_tpu.tools.distill import (
        harvest,
        read_records,
        record_from_detections,
        write_records,
    )

    # one response with mixed quality: low-score dropped, degenerate
    # box dropped, out-of-range class dropped, good boxes clipped
    dets = [
        None,
        np.array([[10, 10, 60, 70, 0.9], [5, 5, 6, 6, 0.95],
                  [0, 0, 30, 40, 0.2]], np.float32),
        np.array([[-20, 15, 90, 200, 0.8]], np.float32),
        np.array([[40, 40, 100, 100, 0.99]], np.float32),  # class 3
    ]
    rec = record_from_detections(dets, 128, 128, index=0, min_score=0.5,
                                 seed=5, num_classes=3)
    assert rec["gt_classes"].tolist() == [1, 2]  # class 3 dropped
    assert rec["boxes"].dtype == np.float32
    assert rec["gt_classes"].dtype == np.int32
    assert float(rec["boxes"].max()) <= 127.0 and float(rec["boxes"].min()) >= 0.0
    ref = SyntheticDataset(num_images=1, num_classes=4,
                           image_size=(128, 128)).gt_roidb()[0]
    assert set(rec) == set(ref)
    for k in ref:
        assert type(rec[k]) is type(ref[k]), k

    # nothing confident → no record
    assert record_from_detections([None, np.zeros((0, 5), np.float32)],
                                  128, 128, index=1) is None

    # unique URIs + seeds per record: the loader's render cache keys on
    # (image, flipped, seed), so two distilled records must never alias
    responses = [(dets, (128, 128))] * 4
    records = harvest(responses, min_score=0.5, seed=5, num_classes=3)
    assert len(records) == 4
    assert len({r["image"] for r in records}) == 4
    assert len({r["synthetic_seed"] for r in records}) == 4

    # JSONL round-trip is exact
    path = str(tmp_path / "distilled.jsonl")
    assert write_records(records, path) == 4
    back = read_records(path)
    for a, b in zip(records, back):
        assert set(a) == set(b)
        np.testing.assert_array_equal(a["boxes"], b["boxes"])
        np.testing.assert_array_equal(a["gt_classes"], b["gt_classes"])
        assert b["boxes"].dtype == np.float32
        assert b["gt_classes"].dtype == np.int32

    # the REAL loader batches them
    cfg = generate_config("resnet50", "PascalVOC")
    cfg = cfg.replace(
        SHAPE_BUCKETS=((128, 128),),
        dataset=dataclasses.replace(
            cfg.dataset, NUM_CLASSES=4, SCALES=((128, 128),), MAX_GT_BOXES=8
        ),
    )
    loader = TrainLoader(back, cfg, 2, shuffle=False, prefetch=0)
    batches = list(loader)
    assert len(batches) == 2
    for b in batches:
        assert b["gt_boxes"].shape[0] == 2
        assert (b["gt_boxes"][:, :, 4] > 0).any()
