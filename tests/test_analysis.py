"""graftlint self-tests: per-rule good/bad fixture matrix, suppression
machinery (inline pragma, baseline, stale detection), the whole-tree
zero-noise guarantee, the runtime lock-order proxy, the faults-spec
hard error, and the BENCH artifact parse guard.

Everything here is stdlib + numpy speed — no jax execution, so the
whole file runs in well under a second of tier-1 budget."""

import json
import threading
import time
from pathlib import Path

import pytest

from mx_rcnn_tpu.analysis import engine as eng
from mx_rcnn_tpu.analysis import lockcheck
from mx_rcnn_tpu.analysis.cli import check_bench_artifacts
from mx_rcnn_tpu.analysis.rules_faults import FaultCoverage
from mx_rcnn_tpu.analysis.rules_futures import ExactlyOnce
from mx_rcnn_tpu.analysis.rules_hostcopy import HostCopyEscape, UseAfterDonate
from mx_rcnn_tpu.analysis.rules_jit import JitPurity
from mx_rcnn_tpu.analysis.rules_locks import LockOrder
from mx_rcnn_tpu.analysis.rules_requeue import BoundedRequeue
from mx_rcnn_tpu.analysis.rules_signals import SignalSafety

REPO = Path(__file__).resolve().parents[1]


def run_rule(src, rule, path="mx_rcnn_tpu/core/mod.py"):
    report = eng.analyze_snippets({path: src}, [rule])
    return report.findings


# ---------------------------------------------------------------- R1

R1_BAD_RETURN = """
import jax

def f(fn, batch):
    return jax.device_get(fn(batch))
"""

R1_BAD_CLOSURE = """
import jax

def g(params):
    host = jax.device_get(params)

    def rebuild():
        return host

    return rebuild
"""

R1_BAD_STORE = """
import jax

class Holder:
    def grab(self, tree):
        self.snapshot = jax.device_get(tree)
"""

R1_GOOD = """
import jax
import numpy as np

def f(fn, batch):
    out = jax.device_get(fn(batch))
    return float(out["loss"].mean())

def g(fn, batch):
    return jax.tree_util.tree_map(np.array, jax.device_get(fn(batch)))

def h(fn, batch, consume):
    consume(jax.device_get(fn(batch)))
"""


def test_r1_fires_on_returned_view():
    fs = run_rule(R1_BAD_RETURN, HostCopyEscape())
    assert len(fs) == 1 and fs[0].rule == "R1" and fs[0].scope == "f"


def test_r1_fires_on_closure_capture():
    fs = run_rule(R1_BAD_CLOSURE, HostCopyEscape())
    assert len(fs) == 1 and "nested function" in fs[0].message


def test_r1_fires_on_attribute_store():
    fs = run_rule(R1_BAD_STORE, HostCopyEscape())
    assert len(fs) == 1 and "stored" in fs[0].message


def test_r1_silent_on_consumed_and_copied():
    assert run_rule(R1_GOOD, HostCopyEscape()) == []


# R1 against the ISSUE 13 split dispatch/complete shape: the completion
# half is exactly where a bare device_get view would escape to a caller
# that outlives the donated buffers

R1_SPLIT_BAD = """
import jax

class Runner:
    def dispatch(self, batch):
        return self._fn(self.params, batch)

    def complete(self, handle):
        return jax.device_get(handle)
"""

R1_SPLIT_GOOD = """
from mx_rcnn_tpu.core.resilience import host_copy

class Runner:
    def dispatch(self, batch):
        return self._fn(self.params, batch)

    def complete(self, handle):
        return host_copy(handle)
"""


def test_r1_fires_on_split_complete_returning_view():
    fs = run_rule(R1_SPLIT_BAD, HostCopyEscape())
    assert len(fs) == 1 and fs[0].rule == "R1"
    assert fs[0].scope == "Runner.complete"


def test_r1_silent_on_split_complete_host_copy():
    assert run_rule(R1_SPLIT_GOOD, HostCopyEscape()) == []


# R1 against the ISSUE 14 mask-fetch shape: the selected det_masks
# tensor crosses to host exactly once, through the owning-copy
# discipline — a bare device_get view of the grids escaping complete()
# is the regression the rule must keep catching
R1_MASK_BAD = """
import jax

class Runner:
    def complete(self, handle):
        out = jax.device_get(handle.outputs)
        return out["det_masks"]
"""

R1_MASK_GOOD = """
from mx_rcnn_tpu.core.resilience import host_copy

class Runner:
    def complete(self, handle):
        out = host_copy(handle.outputs)
        return out["det_masks"]
"""


def test_r1_fires_on_mask_fetch_device_get_view():
    fs = run_rule(R1_MASK_BAD, HostCopyEscape())
    assert len(fs) == 1 and fs[0].rule == "R1"
    assert fs[0].scope == "Runner.complete"


def test_r1_silent_on_mask_fetch_host_copy():
    assert run_rule(R1_MASK_GOOD, HostCopyEscape()) == []


# ---------------------------------------------------------------- R2

R2_BAD = """
import jax

def train(step, state, batch):
    step2 = jax.jit(step, donate_argnums=(0,))
    out = step2(state, batch)
    return state, out
"""

R2_BAD_FACTORY = """
from mx_rcnn_tpu.core.train import make_train_step

def train(model, tx, state, batch, rng):
    step = make_train_step(model, tx, donate=True)
    new_state, aux = step(state, batch, rng)
    print(state)
    return new_state, aux
"""

R2_GOOD = """
import jax

def train(step, state, batch):
    step2 = jax.jit(step, donate_argnums=(0,))
    state = step2(state, batch)
    return state
"""


def test_r2_fires_on_use_after_donate():
    fs = run_rule(R2_BAD, UseAfterDonate())
    assert len(fs) == 1 and "`state` read after being donated" in fs[0].message


def test_r2_fires_on_factory_donation():
    fs = run_rule(R2_BAD_FACTORY, UseAfterDonate())
    assert len(fs) == 1
    assert "`state` read after being donated to `step`" in fs[0].message


def test_r2_silent_on_rebind():
    assert run_rule(R2_GOOD, UseAfterDonate()) == []


# ---------------------------------------------------------------- R3

R3_BAD = """
import jax
from mx_rcnn_tpu.utils import faults

seen = []

@jax.jit
def step(x):
    global seen
    faults.stall(0)
    if float(x.sum()) > 0:
        x = -x
    return x
"""

R3_BAD_WRAPPED = """
import jax

def fwd(p, b):
    if b["flag"].item() > 0:
        return p
    return b

f = jax.jit(fwd, donate_argnums=(1,))
"""

R3_GOOD = """
import jax
import jax.numpy as jnp

@jax.jit
def step(x):
    y = jnp.where(x > 0, -x, x)
    return y

def helper(state):
    # not jitted: host branching is fine here
    if float(state.loss) > 1e4:
        return None
    return state
"""


def test_r3_fires_on_impure_jit_body():
    fs = run_rule(R3_BAD, JitPurity())
    msgs = " | ".join(f.message for f in fs)
    assert len(fs) == 3
    assert "global" in msgs and "faults.stall" in msgs and "float()" in msgs


def test_r3_finds_wrapper_form_jit():
    fs = run_rule(R3_BAD_WRAPPED, JitPurity())
    assert len(fs) == 1 and ".item()" in fs[0].message


def test_r3_silent_on_clean_and_unjitted():
    assert run_rule(R3_GOOD, JitPurity()) == []


# ---------------------------------------------------------------- R4

R4_CYCLE = """
import threading

class Alpha:
    def __init__(self):
        self._lock = threading.Lock()
        self.beta = None

    def do_alpha(self):
        with self._lock:
            self.beta.do_beta()

class Beta:
    def __init__(self):
        self._lock = threading.Lock()
        self.alpha = None

    def do_beta(self):
        with self._lock:
            pass

    def call_back(self):
        with self._lock:
            self.alpha.do_alpha()
"""

R4_DEVICE = """
import threading
import jax

class Holder:
    def __init__(self):
        self._lock = threading.Lock()

    def bad(self, tree):
        with self._lock:
            return jax.device_put(tree)

    def good(self, tree):
        out = jax.device_put(tree)
        with self._lock:
            self.count = 1
        return out
"""

R4_GOOD = """
import threading

class Alpha:
    def __init__(self):
        self._lock = threading.Lock()
        self.beta = None

    def do_alpha(self):
        with self._lock:
            self.beta.do_beta()

class Beta:
    def __init__(self):
        self._lock = threading.Lock()

    def do_beta(self):
        with self._lock:
            pass
"""

R4_MAKE_LOCK = """
from mx_rcnn_tpu.analysis.lockcheck import make_lock
import jax

class Holder:
    def __init__(self):
        self._lock = make_lock("Holder._lock")

    def bad(self, tree):
        with self._lock:
            return jax.jit(tree)
"""


def test_r4_fires_on_lock_cycle():
    fs = run_rule(R4_CYCLE, LockOrder(), path="mx_rcnn_tpu/serve/fx.py")
    assert any("cycle" in f.message for f in fs)


def test_r4_fires_on_device_put_under_lock():
    fs = run_rule(R4_DEVICE, LockOrder(), path="mx_rcnn_tpu/serve/fx.py")
    assert len(fs) == 1
    assert fs[0].scope == "Holder.bad" and "device" in fs[0].message


def test_r4_recognizes_make_lock_spelling():
    fs = run_rule(R4_MAKE_LOCK, LockOrder(), path="mx_rcnn_tpu/serve/fx.py")
    assert len(fs) == 1 and "Holder._lock" in fs[0].message


def test_r4_silent_on_one_way_order():
    assert run_rule(R4_GOOD, LockOrder(), path="mx_rcnn_tpu/serve/fx.py") == []


def test_r4_ignores_non_serve_modules():
    assert run_rule(R4_DEVICE, LockOrder(), path="mx_rcnn_tpu/core/fx.py") == []


# R4 against the ISSUE 16 tenancy shape: the batcher's WFQ release path
# holds the batcher condition and calls the tenant table's weight()
# (which takes TenantTable._lock as a leaf).  One-way is the shipped
# design; a table method that calls BACK into the batcher under its own
# lock closes the cycle graftlint must flag.

R4_TENANCY_BAD = """
from mx_rcnn_tpu.analysis.lockcheck import make_lock

class Batcher:
    def __init__(self):
        self._cond = make_lock("Batcher._cond")
        self.table = None

    def release(self):
        with self._cond:
            return self.table.weight("acme")

class Table:
    def __init__(self):
        self._lock = make_lock("Table._lock")
        self.batcher = None

    def weight(self, tenant):
        with self._lock:
            return 1.0

    def over_share(self, tenant):
        with self._lock:
            return self.batcher.release()
"""

R4_TENANCY_GOOD = """
from mx_rcnn_tpu.analysis.lockcheck import make_lock

class Batcher:
    def __init__(self):
        self._cond = make_lock("Batcher._cond")
        self.table = None

    def release(self):
        with self._cond:
            return self.table.weight("acme")

class Table:
    def __init__(self):
        self._lock = make_lock("Table._lock")

    def weight(self, tenant):
        with self._lock:
            return 1.0
"""


def test_r4_fires_on_tenancy_lock_cycle():
    fs = run_rule(R4_TENANCY_BAD, LockOrder(),
                  path="mx_rcnn_tpu/serve/tenancy.py")
    assert any("cycle" in f.message for f in fs)


def test_r4_silent_on_tenancy_leaf_order():
    assert run_rule(R4_TENANCY_GOOD, LockOrder(),
                    path="mx_rcnn_tpu/serve/tenancy.py") == []


# ---------------------------------------------------------------- R5

R5_BAD = """
class Worker:
    def loop(self):
        while True:
            d = self._inbox.get()
            if self._stop:
                return
            d.resolve(1)
"""

R5_GOOD = """
class Worker:
    def loop(self):
        while True:
            d = self._inbox.get(timeout=0.02)
            if d is None:
                break
            self._serve(d)

    def drain(self):
        while True:
            try:
                d = self._inbox.get_nowait()
            except Exception:
                break
            if d is not None:
                d.resolve(None)
"""


def test_r5_fires_on_droppable_take():
    fs = run_rule(R5_BAD, ExactlyOnce(), path="mx_rcnn_tpu/serve/fx.py")
    assert len(fs) == 1 and "`d`" in fs[0].message


def test_r5_silent_on_sentinel_and_drain():
    assert run_rule(R5_GOOD, ExactlyOnce(), path="mx_rcnn_tpu/serve/fx.py") == []


# R5 against the ISSUE 13 overlapped window: the local ``pending`` deque
# is a take source too — popping the oldest entry and then leaving the
# scope without settling it drops a windowed dispatch

R5_OVERLAP_BAD = """
class Worker:
    def loop(self):
        pending = deque()
        while True:
            d = self._inbox.get(timeout=0.02)
            if d is None:
                break
            pending.append(self._begin(d))
            entry = pending.popleft()
            if self._stop:
                return
            self._finish(entry)
"""

R5_OVERLAP_GOOD = """
class Worker:
    def loop(self):
        pending = deque()
        while not self._stop:
            d = self._inbox.get(timeout=0.02)
            if d is None:
                break
            pending.append(self._begin(d))
            if pending:
                entry = pending.popleft()
                self._finish(entry)
"""


def test_r5_fires_on_droppable_window_entry():
    fs = run_rule(R5_OVERLAP_BAD, ExactlyOnce(),
                  path="mx_rcnn_tpu/serve/fx.py")
    assert len(fs) == 1 and "`entry`" in fs[0].message


def test_r5_silent_on_settled_window_entry():
    assert run_rule(R5_OVERLAP_GOOD, ExactlyOnce(),
                    path="mx_rcnn_tpu/serve/fx.py") == []


# R5 against the ISSUE 16 scale-down drain: the victim replica's queued
# dispatches are a take source; popping one and bailing on the stop
# flag without requeuing it on a sibling is a dropped request — exactly
# the loss the zero-loss shrink bench would catch after the fact, and
# graftlint flags at review time

R5_DRAIN_BAD = """
class Drainer:
    def drain_victim(self):
        while True:
            d = self._victim_queue.get(timeout=0.02)
            if self._stop:
                return
            if d is None:
                break
            self._sibling.dispatch(d)
"""

R5_DRAIN_GOOD = """
class Drainer:
    def drain_victim(self):
        while True:
            d = self._victim_queue.get(timeout=0.02)
            if d is None:
                break
            self._sibling.dispatch(d)
"""


def test_r5_fires_on_dropped_drain_dispatch():
    fs = run_rule(R5_DRAIN_BAD, ExactlyOnce(),
                  path="mx_rcnn_tpu/serve/autoscaler.py")
    assert len(fs) == 1 and "`d`" in fs[0].message


def test_r5_silent_on_requeued_drain_dispatch():
    assert run_rule(R5_DRAIN_GOOD, ExactlyOnce(),
                    path="mx_rcnn_tpu/serve/autoscaler.py") == []


# R4 against the ISSUE 20 streaming gate: the engine resolves a request
# under Engine._lock and calls StreamTable.settle (a leaf); a table
# that fires the settlement callback while still HOLDING
# StreamTable._lock calls back into the engine and closes the cycle.
# The drainer discipline (collect the ready run under the lock, fire
# after release) is the shipped one-way design.

R4_STREAMS_BAD = """
from mx_rcnn_tpu.analysis.lockcheck import make_lock

class Engine:
    def __init__(self):
        self._lock = make_lock("Engine._lock")
        self.streams = None

    def resolve(self, req):
        with self._lock:
            return self.streams.settle(req)

class StreamTable:
    def __init__(self):
        self._lock = make_lock("StreamTable._lock")
        self.engine = None

    def settle(self, req):
        with self._lock:
            return self.engine.resolve(req)
"""

R4_STREAMS_GOOD = """
from mx_rcnn_tpu.analysis.lockcheck import make_lock

class Engine:
    def __init__(self):
        self._lock = make_lock("Engine._lock")
        self.streams = None

    def resolve(self, req):
        with self._lock:
            return self.streams.settle(req)

class StreamTable:
    def __init__(self):
        self._lock = make_lock("StreamTable._lock")

    def settle(self, req):
        with self._lock:
            run = [req]
        for fire in run:
            fire()
        return True
"""


def test_r4_fires_on_stream_settle_cycle():
    fs = run_rule(R4_STREAMS_BAD, LockOrder(),
                  path="mx_rcnn_tpu/serve/streams.py")
    assert any("cycle" in f.message for f in fs)


def test_r4_silent_on_stream_drainer_discipline():
    assert run_rule(R4_STREAMS_GOOD, LockOrder(),
                    path="mx_rcnn_tpu/serve/streams.py") == []


# R5 against the ISSUE 20 in-order buffer: a parked settlement callback
# popped off the buffer and then dropped on a shutdown flag is a frame
# the client never hears about — the stream's successors are wedged
# behind the gap forever.  The shipped flush() drains every taken
# callback (sentinel break + resolve-all drain).

R5_STREAMS_BAD = """
class StreamTable:
    def flush(self):
        while True:
            fire = self._pending.get(timeout=0.02)
            if self._closed:
                return
            fire.resolve(None)
"""

R5_STREAMS_GOOD = """
class StreamTable:
    def loop(self):
        while True:
            fire = self._pending.get(timeout=0.02)
            if fire is None:
                break
            self._fire(fire)

    def flush(self):
        while True:
            try:
                fire = self._pending.get_nowait()
            except Exception:
                break
            if fire is not None:
                fire.resolve(None)
"""


def test_r5_fires_on_dropped_buffered_settlement():
    fs = run_rule(R5_STREAMS_BAD, ExactlyOnce(),
                  path="mx_rcnn_tpu/serve/streams.py")
    assert len(fs) == 1 and "`fire`" in fs[0].message


def test_r5_silent_on_stream_flush_drain():
    assert run_rule(R5_STREAMS_GOOD, ExactlyOnce(),
                    path="mx_rcnn_tpu/serve/streams.py") == []


# ---------------------------------------------------------------- R6

R6_FAULTS = """
def _active():
    return []

def hook_a():
    for f in _active():
        if f.kind == "ka":
            pass

def hook_b():
    for f in _active():
        if f.kind == "kb":
            pass
"""

R6_CALLER_OK = """
from mx_rcnn_tpu.utils import faults

def run():
    faults.hook_a()
    faults.hook_b()
"""

R6_CALLER_BAD = """
from mx_rcnn_tpu.utils import faults

def run():
    faults.hook_a()
    faults.missing_hook()
"""

FAULTS_PATH = "mx_rcnn_tpu/utils/faults.py"


def test_r6_fires_on_uncovered_and_nonexistent_hooks():
    report = eng.analyze_snippets(
        {FAULTS_PATH: R6_FAULTS, "mx_rcnn_tpu/core/use.py": R6_CALLER_BAD},
        [FaultCoverage()],
    )
    msgs = " | ".join(f.message for f in report.findings)
    assert "missing_hook" in msgs and "hook_b" in msgs


def test_r6_silent_when_hooks_covered():
    report = eng.analyze_snippets(
        {FAULTS_PATH: R6_FAULTS, "mx_rcnn_tpu/core/use.py": R6_CALLER_OK},
        [FaultCoverage()],
    )
    assert report.findings == []


def test_r6_fires_on_known_kinds_drift():
    drift = R6_FAULTS + '\n_KNOWN_KINDS = frozenset({"ka"})\n'
    report = eng.analyze_snippets(
        {FAULTS_PATH: drift, "mx_rcnn_tpu/core/use.py": R6_CALLER_OK},
        [FaultCoverage()],
    )
    assert any("_KNOWN_KINDS drift" in f.message for f in report.findings)
    assert any("'kb'" in f.message for f in report.findings)


# ---------------------------------------------------------------- R7

R7_BAD = """
import signal
import threading
import jax
from mx_rcnn_tpu.utils import faults

class Guard:
    def __init__(self):
        self._lock = threading.Lock()
        signal.signal(signal.SIGTERM, self._handle)

    def _handle(self, signum, frame):
        with self._lock:
            self.flag = True
        faults.crash_save()
        self._snapshot()

    def _snapshot(self):
        self.snap = jax.device_get(self.state)
"""

R7_BAD_MODULE_FN = """
import signal

def _save():
    from mx_rcnn_tpu.core.resilience import host_copy
    return host_copy({})

def handler(signum, frame):
    _save()

signal.signal(signal.SIGINT, handler)
"""

R7_BAD_ACQUIRE = """
import signal

class G:
    def _handle(self, signum, frame):
        self.mu.acquire()

    def install(self):
        signal.signal(signal.SIGTERM, self._handle)
"""

R7_GOOD = """
import os
import signal

class Guard:
    def __init__(self, signals=(signal.SIGTERM,)):
        self.should_stop = False
        self._prev = {}
        for s in signals:
            self._prev[s] = signal.signal(s, self._handle)

    def _handle(self, signum, frame):
        if self.should_stop:
            signal.signal(signum, self._prev[signum])
            os.kill(os.getpid(), signum)
        self.should_stop = True
"""


def test_r7_fires_on_lock_device_and_faults_in_handler():
    fs = run_rule(R7_BAD, SignalSafety())
    msgs = " | ".join(f.message for f in fs)
    assert "acquires lock `_lock`" in msgs
    assert "fault-injection hook `faults.crash_save`" in msgs
    # transitive: the device_get lives in a self.* callee of the handler
    assert "device/placement work `jax.device_get`" in msgs
    assert all("signal handler `Guard._handle`" in f.message for f in fs)


def test_r7_follows_module_function_handler():
    fs = run_rule(R7_BAD_MODULE_FN, SignalSafety())
    assert len(fs) == 1 and "host_copy" in fs[0].message


def test_r7_fires_on_explicit_acquire():
    fs = run_rule(R7_BAD_ACQUIRE, SignalSafety())
    assert len(fs) == 1 and ".acquire()" in fs[0].message


def test_r7_silent_on_flag_flip_handler():
    """The PreemptionGuard shape — flag, handler restore, os.kill
    re-raise — is the sanctioned handler body and must be clean."""
    assert run_rule(R7_GOOD, SignalSafety()) == []


# ---------------------------------------------------------------- R8

R8_BAD_LOOP = """
class Router:
    def run(self, batch):
        while True:
            try:
                d = self.replica.submit(batch)
                return d.future.result()
            except Exception:
                continue
"""

R8_BAD_RETRY_FN = """
class Engine:
    def _resubmit(self, req):
        self.batcher.submit(req)
"""

R8_GOOD_DIRECT_SPEND = """
class Router:
    def run(self, batch, budget):
        while True:
            try:
                d = self.replica.submit(batch)
                return d.future.result()
            except Exception:
                budget.spend("requeue")
"""

R8_GOOD_INDIRECT_SPEND = """
class Engine:
    def _charge(self, req):
        req.budget.spend("resubmit")

    def _resubmit(self, req):
        self._charge(req)
        self.batcher.submit(req)
"""

R8_GOOD_INTAKE = """
def client(engine, im):
    while True:
        try:
            return engine.submit(im)
        except Exception:
            continue
"""

SERVE_PATH = "mx_rcnn_tpu/serve/fx.py"


def test_r8_fires_on_looped_requeue_without_budget():
    fs = run_rule(R8_BAD_LOOP, BoundedRequeue(), path=SERVE_PATH)
    assert len(fs) == 1 and fs[0].rule == "R8"
    assert "inside a loop" in fs[0].message


def test_r8_fires_in_retry_named_function():
    fs = run_rule(R8_BAD_RETRY_FN, BoundedRequeue(), path=SERVE_PATH)
    assert len(fs) == 1 and "retry path" in fs[0].message


def test_r8_silent_when_budget_spent_directly():
    assert run_rule(R8_GOOD_DIRECT_SPEND, BoundedRequeue(),
                    path=SERVE_PATH) == []


def test_r8_silent_when_spend_reached_through_helper():
    assert run_rule(R8_GOOD_INDIRECT_SPEND, BoundedRequeue(),
                    path=SERVE_PATH) == []


def test_r8_silent_on_intake_submit_and_out_of_scope():
    # engine.submit is intake, not re-dispatch — not a requeue receiver
    assert run_rule(R8_GOOD_INTAKE, BoundedRequeue(), path=SERVE_PATH) == []
    # same unbounded loop outside /serve/ is out of scope
    assert run_rule(R8_BAD_LOOP, BoundedRequeue()) == []


# ------------------------------------------------- suppression layers


def test_inline_pragma_suppresses_with_reason():
    src = R1_BAD_RETURN.replace(
        "return jax.device_get(fn(batch))",
        "return jax.device_get(fn(batch))  "
        "# graftlint: disable=R1(outputs never donated)",
    )
    report = eng.analyze_snippets(
        {"mx_rcnn_tpu/core/mod.py": src}, [HostCopyEscape()]
    )
    assert report.findings == []
    assert len(report.inline_suppressed) == 1
    assert report.inline_suppressed[0][1] == "outputs never donated"


def test_inline_pragma_without_reason_is_ignored():
    src = R1_BAD_RETURN.replace(
        "return jax.device_get(fn(batch))",
        "return jax.device_get(fn(batch))  # graftlint: disable=R1",
    )
    report = eng.analyze_snippets(
        {"mx_rcnn_tpu/core/mod.py": src}, [HostCopyEscape()]
    )
    assert len(report.findings) == 1


def test_baseline_suppresses_and_flags_stale():
    good = eng.BaselineEntry(
        rule="R1", path="mx_rcnn_tpu/core/mod.py", scope="f", reason="known"
    )
    stale = eng.BaselineEntry(
        rule="R1", path="mx_rcnn_tpu/core/gone.py", scope="g", reason="old"
    )
    report = eng.analyze_snippets(
        {"mx_rcnn_tpu/core/mod.py": R1_BAD_RETURN},
        [HostCopyEscape()],
        baseline=[good, stale],
    )
    assert report.findings == []
    assert len(report.baseline_suppressed) == 1
    assert report.stale_baseline == [stale]
    assert not report.ok  # stale entries fail the run


# ------------------------------------------------- whole-tree guards


@pytest.fixture(scope="module")
def tree():
    modules, errors = eng.load_modules(REPO)
    baseline = eng.load_baseline(REPO / "tools" / "lint_baseline.json")
    return modules, baseline, errors


def test_tree_is_clean(tree):
    modules, baseline, errors = tree
    report = eng.analyze(modules, eng.default_rules(), baseline, errors)
    detail = "\n".join(f.format() for f in report.findings)
    assert report.ok, f"{report.summary()}\n{detail}"


def test_fresh_r1_violation_fails_the_tree(tree):
    modules, baseline, errors = tree
    injected = eng.Module("mx_rcnn_tpu/core/_fresh_violation.py", R1_BAD_RETURN)
    report = eng.analyze(
        list(modules) + [injected], eng.default_rules(), baseline, errors
    )
    assert not report.ok
    assert any(
        f.rule == "R1" and f.path.endswith("_fresh_violation.py")
        for f in report.findings
    )


def test_fabricated_stale_entry_fails_the_tree(tree):
    modules, baseline, errors = tree
    fake = eng.BaselineEntry(
        rule="R1", path="mx_rcnn_tpu/core/nope.py", scope="*", reason="stale"
    )
    report = eng.analyze(
        modules, eng.default_rules(), list(baseline) + [fake], errors
    )
    assert not report.ok and fake in report.stale_baseline


# ------------------------------------------------- runtime lock check


@pytest.fixture(autouse=True)
def _fresh_lock_graph():
    lockcheck.reset()
    yield
    lockcheck.reset()


def test_lockcheck_raises_on_inversion():
    a = lockcheck.OrderedLock("A")
    b = lockcheck.OrderedLock("B")
    with a:
        with b:
            pass
    with b:
        with pytest.raises(lockcheck.LockOrderViolation):
            a.acquire()


def test_lockcheck_allows_consistent_order():
    a = lockcheck.OrderedLock("A")
    b = lockcheck.OrderedLock("B")
    for _ in range(3):
        with a:
            with b:
                pass


def test_lockcheck_same_name_instances_nest():
    # LatencyHistogram.merge holds two instances of the same lock class
    h1 = lockcheck.OrderedLock("H")
    h2 = lockcheck.OrderedLock("H")
    with h1:
        with h2:
            pass


def test_lockcheck_rlock_reentry_ok_plain_reentry_raises():
    r = lockcheck.OrderedLock("R", rlock=True)
    with r:
        with r:
            pass
    p = lockcheck.OrderedLock("P")
    with p:
        with pytest.raises(lockcheck.LockOrderViolation):
            p.acquire()


def test_lockcheck_disabled_returns_plain_primitives(monkeypatch):
    monkeypatch.delenv("MX_RCNN_LOCK_CHECK", raising=False)
    assert not isinstance(lockcheck.make_lock("X"), lockcheck.OrderedLock)
    monkeypatch.setenv("MX_RCNN_LOCK_CHECK", "1")
    assert isinstance(lockcheck.make_lock("X"), lockcheck.OrderedLock)


def test_lockcheck_condition_proxy_wait_notify(monkeypatch):
    monkeypatch.setenv("MX_RCNN_LOCK_CHECK", "1")
    cond = lockcheck.make_condition("C")
    hits = []

    def waiter():
        with cond:
            hits.append(cond.wait(timeout=2.0))

    t = threading.Thread(target=waiter)
    t.start()
    deadline = time.time() + 2.0
    while time.time() < deadline:
        with cond:
            cond.notify_all()
        if hits:
            break
        time.sleep(0.01)
    t.join(timeout=2.0)
    assert hits == [True]


# ------------------------------------------------- faults spec errors


def test_unknown_fault_kind_is_hard_error(monkeypatch):
    from mx_rcnn_tpu.utils import faults

    monkeypatch.setenv("MX_RCNN_FAULTS", "predict_fial@0.1")
    faults.reset()
    with pytest.raises(ValueError, match="predict_fial"):
        faults.predict_fault(0, 1)
    monkeypatch.setenv("MX_RCNN_FAULTS", "")
    faults.reset()


def test_valid_fault_specs_still_parse(monkeypatch):
    from mx_rcnn_tpu.utils import faults

    monkeypatch.setenv(
        "MX_RCNN_FAULTS", "nan_loss@3,predict_fail@0.1x2,swap_verify_fail@*"
    )
    faults.reset()
    # wrong keys: parses fine, fires nothing
    faults.corrupt_loss(0.5, None)
    monkeypatch.setenv("MX_RCNN_FAULTS", "")
    faults.reset()


# ------------------------------------------------- bench artifacts


def test_bench_artifacts_parse():
    assert check_bench_artifacts(REPO) == []
    found = sorted(p.name for p in REPO.glob("BENCH_*.json"))
    assert found, "committed BENCH_*.json artifacts should exist"
    for p in REPO.glob("BENCH_*.json"):
        doc = json.loads(p.read_text())
        assert isinstance(doc, (dict, list)) and doc


def test_elastic_artifact_schema_guard(tmp_path):
    """BENCH_elastic_cpu.json must carry all four chaos scenarios, each
    with the zero-lost / bit-identical / recovery fields — a bench
    refactor dropping one is a lint failure, not a silent hole."""
    good = {
        "records": [],
        "report": {
            "scenarios": {
                name: {
                    "recovery_s": 0.1,
                    "zero_lost_steps": True,
                    "bit_identical": True,
                }
                for name in (
                    "lose_1_of_8", "wedge", "lose_then_regrow",
                    "preempt_during_shrink",
                )
            }
        },
    }
    art = tmp_path / "BENCH_elastic_cpu.json"
    art.write_text(json.dumps(good))
    assert check_bench_artifacts(tmp_path) == []

    del good["report"]["scenarios"]["wedge"]
    good["report"]["scenarios"]["lose_1_of_8"].pop("bit_identical")
    art.write_text(json.dumps(good))
    errs = " | ".join(check_bench_artifacts(tmp_path))
    assert "scenario 'wedge' missing" in errs
    assert "'lose_1_of_8' missing 'bit_identical'" in errs


def test_poison_artifact_schema_guard(tmp_path):
    """BENCH_poison_cpu.json must carry the four ISSUE 12 containment
    claims — all true — plus a non-empty poison digest list and the
    per-claim metric records."""
    claims = {
        "zero_healthy_lost": True,
        "healthy_byte_identical": True,
        "poison_quarantined_within_k": True,
        "all_replicas_healthy": True,
    }
    good = {
        "records": [
            {"metric": f"serve_poison_{m}_r50", "value": 1}
            for m in ("healthy_lost", "healthy_byte_identical",
                      "quarantined_within_k", "replicas_healthy")
        ],
        "report": {"claims": dict(claims), "digests": ["abc123"]},
    }
    art = tmp_path / "BENCH_poison_cpu.json"
    art.write_text(json.dumps(good))
    assert check_bench_artifacts(tmp_path) == []

    good["report"]["claims"]["healthy_byte_identical"] = False
    del good["report"]["claims"]["all_replicas_healthy"]
    good["report"]["digests"] = []
    good["records"] = good["records"][1:]
    art.write_text(json.dumps(good))
    errs = " | ".join(check_bench_artifacts(tmp_path))
    assert "'healthy_byte_identical' not true" in errs
    assert "'all_replicas_healthy' missing" in errs
    assert "digests empty" in errs
    assert "no record metric 'serve_poison_healthy_lost*'" in errs


def test_overlap_artifact_schema_guard(tmp_path):
    """BENCH_serve_overlap_cpu.json must carry the four ISSUE 13
    acceptance claims — all true — plus per-depth device-busy fractions
    and the speedup/identity/fault metric records."""
    claims = {
        "speedup_ge_1_3": True,
        "byte_identical": True,
        "zero_lost_under_faults": True,
        "zero_steady_state_recompiles": True,
    }
    good = {
        "records": [
            {"metric": m, "value": 1}
            for m in ("serve_overlap_speedup",
                      "serve_overlap_byte_identical",
                      "serve_overlap_fault_lost",
                      "serve_overlap_steady_state_compile_misses")
        ],
        "report": {
            "claims": dict(claims),
            "depth1": {"device_busy_fraction": 0.6},
            "depth2": {"device_busy_fraction": 0.95},
        },
    }
    art = tmp_path / "BENCH_serve_overlap_cpu.json"
    art.write_text(json.dumps(good))
    assert check_bench_artifacts(tmp_path) == []

    good["report"]["claims"]["speedup_ge_1_3"] = False
    del good["report"]["claims"]["byte_identical"]
    del good["report"]["depth2"]["device_busy_fraction"]
    good["records"] = good["records"][1:]
    art.write_text(json.dumps(good))
    errs = " | ".join(check_bench_artifacts(tmp_path))
    assert "'speedup_ge_1_3' not true" in errs
    assert "'byte_identical' missing" in errs
    assert "depth2.device_busy_fraction missing" in errs
    assert "no record metric 'serve_overlap_speedup*'" in errs


def test_mask_artifact_schema_guard(tmp_path):
    """BENCH_serve_mask_cpu.json must carry the three ISSUE 14 closure
    claims — all true — plus the measured fetch-byte evidence and the
    serve_mask metric records."""
    claims = {
        "fetch_reduction_ge_5x": True,
        "rle_byte_identical": True,
        "zero_steady_state_recompiles": True,
    }
    good = {
        "records": [
            {"metric": m, "value": 1}
            for m in ("serve_mask_p50_ms",
                      "serve_mask_p99_ms",
                      "serve_mask_fetch_bytes_per_batch_raw",
                      "serve_mask_fetch_bytes_per_batch_device",
                      "serve_mask_fetch_reduction",
                      "serve_mask_rle_byte_identical",
                      "serve_mask_steady_state_compile_misses")
        ],
        "report": {
            "claims": dict(claims),
            "fetch_bytes": {
                "raw_per_batch": 3237120.0,
                "device_per_batch": 205056.0,
                "reduction": 15.79,
            },
        },
    }
    art = tmp_path / "BENCH_serve_mask_cpu.json"
    art.write_text(json.dumps(good))
    assert check_bench_artifacts(tmp_path) == []

    good["report"]["claims"]["fetch_reduction_ge_5x"] = False
    del good["report"]["claims"]["rle_byte_identical"]
    del good["report"]["fetch_bytes"]["reduction"]
    good["records"] = good["records"][1:]
    art.write_text(json.dumps(good))
    errs = " | ".join(check_bench_artifacts(tmp_path))
    assert "'fetch_reduction_ge_5x' not true" in errs
    assert "'rle_byte_identical' missing" in errs
    assert "fetch_bytes incomplete" in errs
    assert "no record metric 'serve_mask_p50_ms*'" in errs


# R4 against the ISSUE 17 rollout shape: the controller lock guards
# only the split/shadow tables — device work (shadow scoring, warm
# placement) and registry calls happen OUTSIDE it.  A controller that
# scores under its own lock, or a registry→runner→registry call chain
# that closes the lock cycle the promote path walks, is exactly what
# R4 must flag.

R4_ROLLOUT_BAD = """
from mx_rcnn_tpu.analysis.lockcheck import make_lock
import jax

class RolloutController:
    def __init__(self):
        self._lock = make_lock("RolloutController._lock")
        self.registry = None

    def score_shadow(self, tree):
        with self._lock:
            return jax.device_put(tree)

class ModelRegistry:
    def __init__(self):
        self._lock = make_lock("ModelRegistry._lock")
        self.runner = None

    def commit(self):
        with self._lock:
            return self.runner.sync()

class ServeRunner:
    def __init__(self):
        self._lock = make_lock("ServeRunner._lock")
        self.registry = None

    def sync(self):
        with self._lock:
            return self.registry.commit()
"""

R4_ROLLOUT_GOOD = """
from mx_rcnn_tpu.analysis.lockcheck import make_lock
import jax

class RolloutController:
    def __init__(self):
        self._lock = make_lock("RolloutController._lock")
        self.registry = None
        self._split = {}

    def close_tables(self):
        with self._lock:
            self._split.clear()

    def promote(self):
        self.close_tables()
        self.registry.commit()

    def score_shadow(self, tree):
        placed = jax.device_put(tree)
        with self._lock:
            self.scored = 1
        return placed

class ModelRegistry:
    def __init__(self):
        self._lock = make_lock("ModelRegistry._lock")

    def commit(self):
        with self._lock:
            return True
"""


def test_r4_fires_on_rollout_device_work_under_controller_lock():
    fs = run_rule(R4_ROLLOUT_BAD, LockOrder(),
                  path="mx_rcnn_tpu/serve/rollout.py")
    assert any(
        f.scope == "RolloutController.score_shadow" and "device" in f.message
        for f in fs
    )
    assert any("cycle" in f.message for f in fs)


def test_r4_silent_on_rollout_tables_then_registry_order():
    assert run_rule(R4_ROLLOUT_GOOD, LockOrder(),
                    path="mx_rcnn_tpu/serve/rollout.py") == []


# R5 against the ISSUE 17 shadow lane: the mirror queue is a take
# source; popping an item under the condition and then bailing on the
# stop flag without scoring it silently drops a comparison the
# promote/rollback verdict was waiting on.  The shipped worker checks
# stop-and-empty BEFORE the pop, so every popped item reaches the
# scorer on every path.

R5_SHADOW_BAD = """
class ShadowWorker:
    def loop(self):
        while True:
            with self._cond:
                item = self._shadow_queue.popleft()
            if self._stop:
                return
            self._score(item)
"""

R5_SHADOW_GOOD = """
class ShadowWorker:
    def loop(self):
        while True:
            with self._cond:
                while not self._shadow_queue and not self._stop:
                    self._cond.wait(0.05)
                if not self._shadow_queue and self._stop:
                    return
                item = self._shadow_queue.popleft()
            self._score(item)
"""


def test_r5_fires_on_droppable_shadow_item():
    fs = run_rule(R5_SHADOW_BAD, ExactlyOnce(),
                  path="mx_rcnn_tpu/serve/rollout.py")
    assert len(fs) == 1 and "`item`" in fs[0].message


def test_r5_silent_on_pop_after_stop_check():
    assert run_rule(R5_SHADOW_GOOD, ExactlyOnce(),
                    path="mx_rcnn_tpu/serve/rollout.py") == []


def test_rollout_artifact_schema_guard(tmp_path):
    """BENCH_rollout_cpu.json must carry the five ISSUE 17 closure
    claims — all true — plus the shadow divergence evidence and the
    rollout metric records."""
    claims = {
        "zero_lost_requests": True,
        "control_arm_byte_identical": True,
        "divergence_auto_rollback": True,
        "zero_steady_state_recompiles": True,
        "closed_loop_promoted": True,
    }
    good = {
        "records": [
            {"metric": m, "value": 1}
            for m in ("rollout_split_served",
                      "rollout_shadow_compared",
                      "rollout_promote_lost_requests",
                      "rollout_rollback_incumbent_identical",
                      "rollout_steady_state_recompiles",
                      "rollout_distill_records",
                      "rollout_loop_promoted_version")
        ],
        "report": {
            "claims": dict(claims),
            "divergence": {"compared": 12, "max_box_delta_px": 0.002},
        },
    }
    art = tmp_path / "BENCH_rollout_cpu.json"
    art.write_text(json.dumps(good))
    assert check_bench_artifacts(tmp_path) == []

    good["report"]["claims"]["divergence_auto_rollback"] = False
    del good["report"]["claims"]["closed_loop_promoted"]
    del good["report"]["divergence"]["compared"]
    good["records"] = good["records"][1:]
    art.write_text(json.dumps(good))
    errs = " | ".join(check_bench_artifacts(tmp_path))
    assert "'divergence_auto_rollback' not true" in errs
    assert "'closed_loop_promoted' missing" in errs
    assert "divergence incomplete" in errs
    assert "no record metric 'rollout_split_served*'" in errs


# R4 against the ISSUE 18 cascade shape: the router lock is a LEAF
# guarding only the gate counters — the confidence gate itself runs on
# host arrays and escalation re-entry goes back through the engine
# OUTSIDE the lock.  A router that touches the device under its own
# lock, or an engine->router->engine call chain that closes a lock
# cycle on the escalation path, is exactly what R4 must flag.

R4_CASCADE_BAD = """
from mx_rcnn_tpu.analysis.lockcheck import make_lock
import jax

class CascadeRouter:
    def __init__(self):
        self._lock = make_lock("CascadeRouter._lock")
        self.engine = None

    def gate(self, dets):
        with self._lock:
            return jax.device_get(dets)

    def record(self, req):
        with self._lock:
            return self.engine.escalate(req)

class ServeEngine:
    def __init__(self):
        self._lock = make_lock("ServeEngine._lock")
        self.router = None

    def escalate(self, req):
        with self._lock:
            return self.router.record(req)
"""

R4_CASCADE_GOOD = """
from mx_rcnn_tpu.analysis.lockcheck import make_lock
import jax

class CascadeRouter:
    def __init__(self):
        self._lock = make_lock("CascadeRouter._lock")
        self.engine = None
        self.escalations = 0

    def gate(self, dets):
        host = jax.device_get(dets)
        with self._lock:
            self.escalations += 1
        return host

    def route(self, req):
        verdict = self.gate(req.dets)
        self.engine.escalate(req)
        return verdict

class ServeEngine:
    def __init__(self):
        self._lock = make_lock("ServeEngine._lock")

    def escalate(self, req):
        with self._lock:
            return True
"""


def test_r4_fires_on_cascade_device_gate_under_router_lock():
    fs = run_rule(R4_CASCADE_BAD, LockOrder(),
                  path="mx_rcnn_tpu/serve/cascade.py")
    assert any(
        f.scope == "CascadeRouter.gate" and "device" in f.message
        for f in fs
    )
    assert any("cycle" in f.message for f in fs)


def test_r4_silent_on_cascade_leaf_lock_counters():
    assert run_rule(R4_CASCADE_GOOD, LockOrder(),
                    path="mx_rcnn_tpu/serve/cascade.py") == []


# R5 against the ISSUE 18 escalation lane: an escalated request popped
# off the re-entry queue and then dropped on the drain flag loses the
# caller's future forever — first-pass results were already discarded
# by the gate, so nobody else will ever settle it.  The shipped path
# checks drain-and-empty BEFORE the pop.

R5_CASCADE_BAD = """
class EscalationWorker:
    def loop(self):
        while True:
            with self._cond:
                req = self._escalation_queue.popleft()
            if self._draining:
                return
            self._resubmit(req)
"""

R5_CASCADE_GOOD = """
class EscalationWorker:
    def loop(self):
        while True:
            with self._cond:
                while not self._escalation_queue and not self._draining:
                    self._cond.wait(0.05)
                if not self._escalation_queue and self._draining:
                    return
                req = self._escalation_queue.popleft()
            self._resubmit(req)
"""


def test_r5_fires_on_droppable_escalated_request():
    fs = run_rule(R5_CASCADE_BAD, ExactlyOnce(),
                  path="mx_rcnn_tpu/serve/cascade.py")
    assert len(fs) == 1 and "`req`" in fs[0].message


def test_r5_silent_on_escalation_pop_after_drain_check():
    assert run_rule(R5_CASCADE_GOOD, ExactlyOnce(),
                    path="mx_rcnn_tpu/serve/cascade.py") == []


def test_cascade_artifact_schema_guard(tmp_path):
    """BENCH_cascade_cpu.json must carry the five ISSUE 18 claims —
    all true — plus the threshold-sweep evidence, the full
    {box,mask} x {f32,bf16,int8} parity matrix, and the cascade metric
    records."""
    claims = {
        "cost_reduction_ge_1p3x_at_matched_accuracy": True,
        "full_escalation_byte_identical": True,
        "zero_steady_state_recompiles": True,
        "int8_parity_ok_box_and_mask": True,
        "bf16_parity_ok_box_and_mask": True,
    }
    good = {
        "records": [
            {"metric": m, "value": 1}
            for m in ("serve_cascade_cost_ms_per_image_matched",
                      "serve_cascade_cost_reduction_x",
                      "serve_cascade_accuracy_matched",
                      "serve_cascade_escalation_rate_matched",
                      "serve_cascade_parity_rungs_ok",
                      "serve_cascade_int8_compression_x_box",
                      "serve_cascade_steady_state_compile_misses")
        ],
        "report": {
            "claims": dict(claims),
            "sweep": [{"min_score": 0.0}, {"min_score": 0.6}],
            "parity_matrix": [
                {"family": f, "precision": p, "ok": True}
                for f in ("box", "mask")
                for p in ("f32", "bf16", "int8")
            ],
        },
    }
    art = tmp_path / "BENCH_cascade_cpu.json"
    art.write_text(json.dumps(good))
    assert check_bench_artifacts(tmp_path) == []

    good["report"]["claims"]["cost_reduction_ge_1p3x_at_matched_accuracy"] = False
    del good["report"]["claims"]["bf16_parity_ok_box_and_mask"]
    good["report"]["sweep"] = good["report"]["sweep"][:1]
    good["report"]["parity_matrix"] = good["report"]["parity_matrix"][1:]
    good["records"] = good["records"][1:]
    art.write_text(json.dumps(good))
    errs = " | ".join(check_bench_artifacts(tmp_path))
    assert "'cost_reduction_ge_1p3x_at_matched_accuracy' not true" in errs
    assert "'bf16_parity_ok_box_and_mask' missing" in errs
    assert "report.sweep missing" in errs
    assert "parity_matrix must cover" in errs
    assert "no record metric 'serve_cascade_cost_ms_per_image*'" in errs


# R4 against the ISSUE 19 fleet gateway: the gateway routes by calling
# into per-backend links, each with its own lock.  Calling a link
# method while holding the gateway lock (or an upcall re-entering the
# gateway under the link lock) closes a gateway->link->gateway cycle —
# the reader thread's response upcall then deadlocks against a
# concurrent submit.  The shipped code computes routing state under
# the gateway lock but always DISPATCHES and upcalls with no lock held.

R4_FLEET_BAD = """
from mx_rcnn_tpu.analysis.lockcheck import make_lock

class BackendLink:
    def __init__(self):
        self._lock = make_lock("BackendLink._lock")
        self.gw = None

    def on_response(self, resp):
        with self._lock:
            return self.gw.finish(resp)

class Gateway:
    def __init__(self):
        self._lock = make_lock("Gateway._lock")
        self.links = [BackendLink()]

    def finish(self, resp):
        with self._lock:
            return resp

    def route(self, req):
        with self._lock:
            return self.links[0].on_response(req)
"""

R4_FLEET_GOOD = """
from mx_rcnn_tpu.analysis.lockcheck import make_lock

class BackendLink:
    def __init__(self):
        self._lock = make_lock("BackendLink._lock")
        self.gw = None
        self.completed = 0

    def on_response(self, resp):
        with self._lock:
            self.completed += 1
        self.gw.finish(resp)

class Gateway:
    def __init__(self):
        self._lock = make_lock("Gateway._lock")
        self.links = [BackendLink()]
        self.routed = 0

    def finish(self, resp):
        with self._lock:
            self.routed += 1

    def route(self, req):
        with self._lock:
            target = self.links[0]
        target.on_response(req)
"""


def test_r4_fires_on_gateway_link_lock_cycle():
    fs = run_rule(R4_FLEET_BAD, LockOrder(),
                  path="mx_rcnn_tpu/serve/fleet.py")
    assert any("cycle" in f.message for f in fs)


def test_r4_silent_on_lockless_gateway_dispatch():
    assert run_rule(R4_FLEET_GOOD, LockOrder(),
                    path="mx_rcnn_tpu/serve/fleet.py") == []


# R5 against the fleet connection pool: a response popped off the
# in-flight correlation map and then dropped on the stopping flag
# strands the caller's future forever — the backend already answered,
# so no requeue path will ever touch that request again.  The shipped
# reader hands EVERY popped entry to the link upcall.

R5_FLEET_BAD = """
class ConnReader:
    def loop(self):
        while True:
            resp = self.read_frame()
            with self._lock:
                entry = self.pending.get(resp["id"])
            if self._stopping:
                return
            self.owner.on_response(entry, resp)
"""

R5_FLEET_GOOD = """
class ConnReader:
    def loop(self):
        while True:
            resp = self.read_frame()
            with self._lock:
                entry = self.pending.get(resp["id"])
            if entry is not None:
                self.owner.on_response(entry, resp)
"""


def test_r5_fires_on_droppable_correlated_response():
    fs = run_rule(R5_FLEET_BAD, ExactlyOnce(),
                  path="mx_rcnn_tpu/serve/fleet.py")
    assert len(fs) == 1 and "`entry`" in fs[0].message


def test_r5_silent_on_response_always_handed_off():
    assert run_rule(R5_FLEET_GOOD, ExactlyOnce(),
                    path="mx_rcnn_tpu/serve/fleet.py") == []


def test_fleet_artifact_schema_guard(tmp_path):
    """BENCH_serve_fleet_cpu.json must carry the five ISSUE 19 claims
    — all true — plus the 1/2/4-backend scaling sweep and the chaos
    kill-phase accounting."""
    claims = {
        "n1_byte_identical": True,
        "scaling_2x": True,
        "scaling_4x": True,
        "chaos_zero_lost": True,
        "chaos_byte_identical": True,
    }
    good = {
        "records": [
            {"metric": m, "value": 1}
            for m in ("serve_fleet_imgs_per_sec_1",
                      "serve_fleet_speedup_2x",
                      "serve_fleet_speedup_4x",
                      "serve_fleet_n1_byte_identical",
                      "serve_fleet_chaos_lost",
                      "serve_fleet_chaos_requeued",
                      "serve_fleet_chaos_byte_identical")
        ],
        "report": {
            "claims": dict(claims),
            "scaling": [
                {"backends": n, "imgs_per_sec": 100.0 * n,
                 "speedup_x": float(n)}
                for n in (1, 2, 4)
            ],
            "chaos": {"lost": 0, "requeued": 3, "byte_identical": True},
        },
    }
    art = tmp_path / "BENCH_serve_fleet_cpu.json"
    art.write_text(json.dumps(good))
    assert check_bench_artifacts(tmp_path) == []

    good["report"]["claims"]["chaos_zero_lost"] = False
    del good["report"]["claims"]["scaling_4x"]
    good["report"]["scaling"] = good["report"]["scaling"][:2]
    del good["report"]["chaos"]["requeued"]
    good["records"] = good["records"][1:]
    art.write_text(json.dumps(good))
    errs = " | ".join(check_bench_artifacts(tmp_path))
    assert "'chaos_zero_lost' not true" in errs
    assert "'scaling_4x' missing" in errs
    assert "report.scaling must cover 1/2/4" in errs
    assert "report.chaos incomplete" in errs
    assert "no record metric 'serve_fleet_imgs_per_sec*'" in errs
