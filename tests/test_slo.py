"""SLO-tiered two-lane scheduling + inference-optimized serve graph
(ISSUE 11).

Three layers, cheapest first, matching the serve-stack test split:

* pure batcher policy (milliseconds, no engine): interactive preemption,
  the two-condition bulk-aging guard, the expired-request sweep;
* engine-level scheduling on a numpy runner stub: interactive latency
  bounded under a saturating bulk backlog, bulk never starved under an
  interactive flood, zero recompiles across lanes, registry SLO-class
  lane defaults, and the idempotent response cache (byte-identity, LRU,
  hot-swap invalidation through a REAL registry swap);
* one real tiny model: the bf16 serve-graph parity gate and its
  precision-tagged compile signatures.

Every test runs with the lock-order checker armed (graftlint R4's
runtime counterpart), same as tests/test_replica.py.
"""

import threading
import time

import numpy as np
import pytest

from mx_rcnn_tpu.core.checkpoint import save_checkpoint
from mx_rcnn_tpu.serve.batcher import (
    DEFAULT_LANE,
    DeadlineExceeded,
    DynamicBatcher,
    QueueFull,
    Request,
)
from mx_rcnn_tpu.serve.buckets import BucketLadder, CompileCache
from mx_rcnn_tpu.serve.engine import ServingEngine
from mx_rcnn_tpu.serve.registry import ModelRegistry
from mx_rcnn_tpu.serve.respcache import ResponseCache


@pytest.fixture(autouse=True)
def _lock_order_check(monkeypatch):
    from mx_rcnn_tpu.analysis import lockcheck

    monkeypatch.setenv("MX_RCNN_LOCK_CHECK", "1")
    lockcheck.reset()
    yield


LADDER = ((32, 32), (48, 64))


def _req(bucket=(32, 32), deadline=None, lane=DEFAULT_LANE, enqueue_t=0.0):
    return Request(
        image=np.zeros((1,), np.uint8),
        im_info=np.array([1.0, 1.0, 1.0], np.float32),
        orig_hw=(1, 1),
        bucket=bucket,
        deadline=deadline,
        lane=lane,
        enqueue_t=enqueue_t,
    )


def image(i: int, h: int = 24, w: int = 24) -> np.ndarray:
    rng = np.random.RandomState(1000 + i)
    return rng.rand(h, w, 3).astype(np.float32)


# ------------------------------------------------------- batcher lane policy
class TestLanePolicy:
    def test_interactive_preempts_waiting_bulk(self):
        b = DynamicBatcher(max_batch=4, max_linger=0.0)
        b.submit(_req(lane="bulk"))
        b.submit(_req(lane="interactive"))
        first = b.next_batch()
        assert [r.lane for r in first] == ["interactive"]
        second = b.next_batch()
        assert [r.lane for r in second] == ["bulk"]
        s = b.stats()
        assert s["preemptions"] == 1
        assert s["batches_by_lane"] == {"interactive": 1, "bulk": 1}

    def test_interactive_zero_linger_releases_batch_of_one(self):
        # bulk linger is huge; the interactive lane must not inherit it
        b = DynamicBatcher(max_batch=4, max_linger=10.0,
                           interactive_linger=0.0)
        b.submit(_req(lane="interactive"))
        t0 = time.monotonic()
        batch = b.next_batch()
        assert len(batch) == 1 and batch[0].lane == "interactive"
        assert time.monotonic() - t0 < 1.0

    def test_aging_guard_needs_head_age_and_release_gap(self):
        now = time.monotonic()
        # both conditions met → bulk takes the slot despite interactive
        b = DynamicBatcher(max_batch=4, max_linger=10.0, bulk_age_limit=0.1)
        b._last_bulk_release = now - 0.2
        b.submit(_req(lane="bulk", enqueue_t=now - 0.2))
        b.submit(_req(lane="interactive"))
        batch = b.next_batch()
        assert [r.lane for r in batch] == ["bulk"]
        assert b.stats()["aged_releases"] == 1

        # head old but bulk released recently (deep-backlog shape) →
        # interactive still wins: the guard is about starvation, and a
        # lane that just got a batch is not starved
        b2 = DynamicBatcher(max_batch=4, max_linger=10.0, bulk_age_limit=0.1)
        b2._last_bulk_release = time.monotonic()
        b2.submit(_req(lane="bulk", enqueue_t=time.monotonic() - 0.2))
        b2.submit(_req(lane="interactive"))
        assert [r.lane for r in b2.next_batch()] == ["interactive"]
        assert b2.stats()["aged_releases"] == 0
        assert b2.stats()["preemptions"] == 1

        # release gap old but head fresh → no starvation yet either
        b3 = DynamicBatcher(max_batch=4, max_linger=10.0, bulk_age_limit=0.1)
        b3._last_bulk_release = time.monotonic() - 0.2
        b3.submit(_req(lane="bulk"))
        b3.submit(_req(lane="interactive"))
        assert [r.lane for r in b3.next_batch()] == ["interactive"]
        assert b3.stats()["aged_releases"] == 0

    def test_unknown_lane_rejected(self):
        b = DynamicBatcher(max_batch=2)
        with pytest.raises(ValueError, match="unknown SLO lane"):
            b.submit(_req(lane="express"))
        assert b.pending() == 0


# --------------------------------------------------------- expired sweep
class TestExpiredSweep:
    def test_submit_sweep_frees_capacity_before_queuefull(self):
        b = DynamicBatcher(max_batch=2, max_linger=10.0, max_queue=1)
        dead = _req(deadline=time.monotonic() - 0.01)
        b.submit(dead)
        live = _req()  # queue is "full" of dead work — must still admit
        b.submit(live)
        assert b.pending() == 1
        assert b.stats()["expired_swept"] == 1
        with pytest.raises(DeadlineExceeded, match="swept from queue"):
            dead.future.result(timeout=0)
        assert not live.future.done()

    def test_next_batch_sweeps_other_groups(self):
        b = DynamicBatcher(max_batch=2, max_linger=0.0)
        dead = _req(bucket=(48, 64), deadline=time.monotonic() - 0.01)
        b.submit(dead)
        b.submit(_req(bucket=(32, 32)))
        batch = b.next_batch()
        assert [r.bucket for r in batch] == [(32, 32)]
        assert b.stats()["expired_swept"] == 1
        assert isinstance(dead.future.exception(timeout=0), DeadlineExceeded)
        assert b.pending() == 0

    def test_on_expired_hook_owns_resolution(self):
        seen = []
        b = DynamicBatcher(max_batch=2, max_linger=10.0, max_queue=4,
                           on_expired=lambda r, now: seen.append(r))
        dead = _req(deadline=time.monotonic() - 0.01)
        b.submit(dead)
        b.submit(_req())
        assert seen == [dead]
        assert not dead.future.done()  # the hook, not the batcher, resolves


# ------------------------------------------------------- engine-level lanes
class FakeRunner:
    """Runner-interface stub (same shape as tests/test_replica.py): real
    ladder/assembly semantics, numpy predict, configurable service time."""

    def __init__(self, service_s: float = 0.0, max_batch: int = 2):
        self.service_s = service_s
        self.ladder = BucketLadder(LADDER)
        self.max_batch = max_batch
        self.cfg = None
        self.compile_cache = CompileCache()
        self.run_calls = 0

    def warmup(self) -> int:
        for bh, bw in self.ladder:
            self.compile_cache.record(((self.max_batch, bh, bw, 3), "f32"))
        return self.compile_cache.misses

    def make_request(self, im, deadline=None) -> Request:
        h, w = im.shape[:2]
        bh, bw = self.ladder.select(h, w)
        canvas = np.zeros((bh, bw, 3), np.float32)
        canvas[:h, :w] = im
        return Request(
            image=canvas,
            im_info=np.array([h, w, 1.0], np.float32),
            orig_hw=(h, w),
            bucket=(bh, bw),
            deadline=deadline,
        )

    def assemble(self, requests):
        images = [r.image for r in requests]
        while len(images) < self.max_batch:
            images.append(images[0])
        return {"images": np.stack(images)}

    def run(self, batch):
        if self.service_s:
            time.sleep(self.service_s)
        self.compile_cache.record((batch["images"].shape, "f32"))
        self.run_calls += 1
        im = batch["images"].astype(np.float64)
        return {"digest": im.sum(axis=(1, 2, 3))}

    def detections_for(self, out, batch, index, orig_hw=None, thresh=None):
        return [np.array([out["digest"][index]])]


class TestEngineTwoLane:
    def test_interactive_bounded_under_saturating_bulk(self):
        # 20 queued bulk requests ≈ 10 batches of service; a tagged
        # probe must ride the next free slot, not the whole backlog
        runner = FakeRunner(service_s=0.03)
        engine = ServingEngine(runner, max_linger=0.0, max_queue=64,
                               in_flight=1, bulk_age_limit=30.0)
        with engine:
            bulk = [engine.submit(image(i)) for i in range(20)]
            probe = engine.submit(image(99), lane="interactive")
            probe.result(timeout=10.0)
            done_bulk = sum(f.done() for f in bulk)
            for f in bulk:
                f.result(timeout=10.0)
        # the probe overtook most of the backlog (generous CI bound: at
        # most half the bulk work may have drained first)
        assert done_bulk <= 10
        snap = engine.snapshot()
        assert snap["scheduler"]["preemptions"] >= 1
        assert snap["lanes"]["interactive"]["completed"] == 1
        assert snap["lanes"]["bulk"]["completed"] == 20

    def test_bulk_never_starved_under_interactive_flood(self):
        runner = FakeRunner(service_s=0.005)
        engine = ServingEngine(runner, max_linger=0.0, max_queue=256,
                               in_flight=1, bulk_age_limit=0.05)
        stop = threading.Event()

        def flood(base):
            # pipeline 8 outstanding per thread: the interactive queue
            # must never drain empty, or bulk could slip into a free
            # slot through the normal path and the aging guard would
            # (legitimately) never fire
            pending, i = [], base
            while not stop.is_set():
                try:
                    pending.append(engine.submit(image(i), lane="interactive"))
                except (QueueFull, RuntimeError):
                    time.sleep(0.002)
                i += 1
                if len(pending) >= 8:
                    try:
                        pending.pop(0).result(timeout=10.0)
                    except RuntimeError:
                        return

        with engine:
            threads = [threading.Thread(target=flood, args=(500 * k,),
                                        daemon=True)
                       for k in range(1, 5)]
            for t in threads:
                t.start()
            time.sleep(0.05)  # flood established before bulk arrives
            bulk = [engine.submit(image(i), lane="bulk") for i in range(6)]
            for f in bulk:
                f.result(timeout=10.0)  # would hang forever if starved
            stop.set()
            for t in threads:
                t.join(timeout=10.0)
        s = engine.snapshot()["scheduler"]
        assert s["aged_releases"] >= 1
        assert s["batches_by_lane"]["bulk"] >= 1
        assert s["batches_by_lane"]["interactive"] >= 1

    def test_zero_recompiles_across_lanes(self):
        runner = FakeRunner()
        warm = runner.warmup()
        engine = ServingEngine(runner, max_linger=0.0)
        with engine:
            futs = [
                engine.submit(image(i, *hw), lane=lane)
                for i, (hw, lane) in enumerate(
                    [((24, 24), "interactive"), ((24, 24), "bulk"),
                     ((32, 48), "interactive"), ((32, 48), "bulk"),
                     ((24, 24), None), ((32, 48), None)]
                )
            ]
            for f in futs:
                f.result(timeout=10.0)
        # lanes schedule batches; they must not mint jit signatures
        assert runner.compile_cache.misses == warm == len(runner.ladder)

    def test_registry_slo_class_sets_default_lane(self):
        reg = ModelRegistry()
        reg.register("det", model=None, cfg=None,
                     params={"w": np.zeros(1, np.float32)},
                     slo_class="interactive")
        runner = FakeRunner()
        runner.registry = reg
        engine = ServingEngine(runner)
        # untagged request inherits the model's registry SLO class;
        # an explicit tag still wins; unknown lanes are rejected
        assert engine._lane_for(None, None) == "interactive"
        assert engine._lane_for("det", "bulk") == "bulk"
        with pytest.raises(ValueError, match="unknown SLO lane"):
            engine._lane_for(None, "express")
        from mx_rcnn_tpu.serve.registry import RegistryError

        with pytest.raises(RegistryError, match="slo_class must be one of"):
            ModelRegistry().register("x", model=None, cfg=None, params={},
                                     slo_class="express")


# ---------------------------------------------------------- response cache
def params_tree(w: float):
    return {"w": np.array([w], np.float32)}


class FakeSwapRunner(FakeRunner):
    """Registry-backed stub with the swap target surface (subset of
    tests/test_registry.py): predict output depends on the live
    version's ``w``, so a stale cache hit would be visible in bytes."""

    def __init__(self, registry, service_s: float = 0.0):
        super().__init__(service_s=service_s)
        self.registry = registry
        self.default_model = registry.default_model
        self._staged = {}

    def warmup(self) -> int:
        # same key shape as run() below — (model, shape, dtype) — so the
        # cache's sorted-signature snapshot stays homogeneous
        for bh, bw in self.ladder:
            self.compile_cache.record(
                (self.default_model, (self.max_batch, bh, bw, 3), "f32")
            )
        return self.compile_cache.misses

    def run(self, batch, model=None):
        mid = model or self.default_model
        live = self.registry.live(mid)
        if self.service_s:
            time.sleep(self.service_s)
        self.compile_cache.record((mid, batch["images"].shape, "f32"))
        self.run_calls += 1
        w = float(np.asarray(live.params["w"]).ravel()[0])
        im = batch["images"].astype(np.float64)
        return {"digest": im.sum(axis=(1, 2, 3)) * (1.0 + w)}

    def detections_for(self, out, batch, index, orig_hw=None, thresh=None,
                       model=None):
        return [np.array([out["digest"][index]])]

    def make_request(self, im, deadline=None, model=None) -> Request:
        r = super().make_request(im, deadline=deadline)
        r.model = model
        return r

    # swap target surface
    def warm_version(self, model, version, params, buckets=None, abort=None):
        self._staged[(model, int(version))] = params
        return 1

    def canary(self, model=None):
        return 1

    def discard_version(self, model, version):
        self._staged.pop((model, int(version)), None)


class TestResponseCache:
    def test_digest_identity_covers_shape_and_dtype(self):
        c = ResponseCache()
        a = np.arange(16, dtype=np.float32)
        assert c.digest(a) == c.digest(a.copy())
        assert c.digest(a) != c.digest(a.reshape(4, 4))  # same bytes
        assert c.digest(a) != c.digest(a.astype(np.float64))
        assert c.digest(a) != c.digest(a + 1)
        assert c.key_for(a, "det", 3) == ("det", 3, "f32", c.digest(a))
        # precision joins the key (ISSUE 18): an int8 serving of the
        # same family/version can never share bytes with the f32 one
        assert c.key_for(a, "det", 3, "int8") == ("det", 3, "int8",
                                                  c.digest(a))
        assert c.key_for(a, "det", 3) != c.key_for(a, "det", 3, "int8")

    def test_lru_no_overwrite_invalidate(self):
        c = ResponseCache(capacity=2)
        c.put(("m", 1, "a"), "A")
        c.put(("m", 1, "a"), "A2")          # no-overwrite: first wins
        assert c.get(("m", 1, "a")) == "A"
        c.put(("m", 1, "b"), "B")
        assert c.get(("m", 1, "a")) == "A"  # refreshes recency
        c.put(("n", 1, "c"), "C")           # evicts LRU ("m",1,"b")
        assert c.get(("m", 1, "b")) is None
        assert c.invalidate_model("m") == 1
        assert c.get(("m", 1, "a")) is None
        assert c.get(("n", 1, "c")) == "C"
        snap = c.snapshot()
        assert snap["size"] == 1
        assert snap["invalidations"] == 1 and snap["evictions"] == 1

    def test_engine_hit_is_byte_identical_and_skips_device(self):
        reg = ModelRegistry()
        reg.register("det", model=None, cfg=None, params=params_tree(1.0))
        runner = FakeSwapRunner(reg)
        cache = ResponseCache(capacity=8)
        engine = ServingEngine(runner, max_linger=0.0, response_cache=cache)
        im = image(1)
        with engine:
            miss = engine.submit(im).result(timeout=10.0)
            calls = runner.run_calls
            hit = engine.submit(im).result(timeout=10.0)
            other = engine.submit(image(2)).result(timeout=10.0)
        assert runner.run_calls >= calls + 1  # the different image ran
        assert len(hit) == len(miss)
        assert all(
            x.tobytes() == y.tobytes() and x.dtype == y.dtype
            for x, y in zip(hit, miss)
        )
        assert not all(
            x.tobytes() == y.tobytes() for x, y in zip(other, miss)
        )
        snap = cache.snapshot()
        assert snap["hits"] == 1 and snap["misses"] == 2
        assert engine.snapshot()["response_cache"]["hits"] == 1

    def test_hot_swap_invalidates_cache(self, tmp_path):
        reg = ModelRegistry()
        reg.register("det", model=None, cfg=None, params=params_tree(1.0))
        runner = FakeSwapRunner(reg)
        cache = ResponseCache(capacity=8)
        engine = ServingEngine(runner, max_linger=0.0, response_cache=cache)
        ckpt = save_checkpoint(
            str(tmp_path / "v2"), {"params": params_tree(2.0)}, 1
        )
        im = image(3)
        with engine:
            v1 = engine.submit(im).result(timeout=10.0)
            assert cache.snapshot()["size"] == 1
            engine.swap("det", ckpt, block=True)
            # the registry's live-pointer hook dropped the entry: the
            # resubmit recomputes under v2 instead of serving stale v1
            assert cache.snapshot()["size"] == 0
            v2 = engine.submit(im).result(timeout=10.0)
            hit2 = engine.submit(im).result(timeout=10.0)
        assert v1[0].tobytes() != v2[0].tobytes()
        assert hit2[0].tobytes() == v2[0].tobytes()
        # the fresh entry is keyed by the NEW live version
        assert any(k[1] == 2 for k in cache._entries)


# -------------------------------------- reduced-precision serve-graph parity
def _tiny_box_model():
    """One real tiny box model (shared by the bf16 and int8 rung tests)."""
    import dataclasses

    import jax

    from mx_rcnn_tpu.config import generate_config
    from mx_rcnn_tpu.models import build_model

    cfg = generate_config("resnet50", "PascalVOC")
    cfg = cfg.replace(
        SHAPE_BUCKETS=((64, 64),),
        network=dataclasses.replace(
            cfg.network, ANCHOR_SCALES=(2, 4, 8), FIXED_PARAMS=()
        ),
        dataset=dataclasses.replace(
            cfg.dataset, NUM_CLASSES=4, SCALES=((48, 64),)
        ),
        TEST=dataclasses.replace(
            cfg.TEST,
            RPN_PRE_NMS_TOP_N=100,
            RPN_POST_NMS_TOP_N=16,
            SCORE_THRESH=0.05,
        ),
    )
    model = build_model(cfg)
    params = model.init(
        {"params": jax.random.key(0)},
        np.zeros((1, 64, 64, 3), np.float32),
        np.array([[64, 64, 1.0]], np.float32),
        train=False,
    )["params"]
    return model, params, cfg


def test_parity_reports_keyed_per_model_and_precision():
    """:attr:`ServeRunner.parity` is keyed ``"model:precision"`` (ISSUE
    18): an int8 report can never satisfy — or be clobbered by — the
    bf16 gate for the same family."""
    from mx_rcnn_tpu.serve.runner import ServeRunner

    r = ServeRunner.__new__(ServeRunner)  # key scheme needs no device
    assert r._parity_key("det", "bf16") == "det:bf16"
    assert r._parity_key("det", "int8") == "det:int8"
    assert r._parity_key("det", "bf16") != r._parity_key("det", "int8")
    assert r._parity_key("det", "bf16") != r._parity_key("seg", "bf16")


@pytest.mark.slow
def test_bf16_parity_gate_and_precision_signatures():
    """One real tiny model served at bf16: warmup must run the f32
    detection-parity gate, pass it, and tag every compile signature with
    the precision so f32/bf16 graphs can never collide in the cache."""
    from mx_rcnn_tpu.serve.runner import ServeRunner

    model, params, cfg = _tiny_box_model()
    runner = ServeRunner(model, params, cfg, max_batch=1,
                         deterministic=True, precision="bfloat16")
    runner.warmup()
    report = runner.parity[f"{runner.default_model}:bf16"]
    assert report["checked"] and report["ok"]
    assert report["precision"] == "bf16"
    assert report["max_box_delta_px"] <= report["box_tol_px"]
    assert report["max_score_delta"] <= report["score_tol"]
    sigs = runner.compile_cache.snapshot()["signatures"]
    assert sigs and all("bf16" in repr(s) for s in sigs)
    # an f32 runner over the same model tags differently — the two
    # serve graphs occupy disjoint compile-cache keys by construction
    f32 = ServeRunner(model, params, cfg, max_batch=1, deterministic=True)
    f32.warmup()
    f32_sigs = f32.compile_cache.snapshot()["signatures"]
    assert all("f32" in repr(s) for s in f32_sigs)
    assert not set(map(repr, sigs)) & set(map(repr, f32_sigs))


@pytest.mark.slow
def test_int8_parity_gate_and_broken_scale_fold_refused():
    """The int8 rung on a real tiny model: warmup folds per-channel
    scales at registry load, runs the same f32 detection-parity gate as
    bf16, and tags compile signatures ``int8``; a deliberately broken
    scale fold must be REFUSED by the gate, not served."""
    import jax

    from mx_rcnn_tpu.core.quantize import is_quantized_leaf
    from mx_rcnn_tpu.serve.runner import PrecisionParityError, ServeRunner

    model, params, cfg = _tiny_box_model()
    runner = ServeRunner(model, params, cfg, max_batch=1,
                         deterministic=True, precision="int8")
    runner.warmup()
    report = runner.parity[f"{runner.default_model}:int8"]
    assert report["checked"] and report["ok"]
    assert report["precision"] == "int8"
    assert report["max_box_delta_px"] <= report["box_tol_px"]
    assert report["max_score_delta"] <= report["score_tol"]
    sigs = runner.compile_cache.snapshot()["signatures"]
    assert sigs and all("int8" in repr(s) for s in sigs)
    # the registry folds scales once per (model, version) and caches
    reg = runner.registry
    assert reg.quantized_tree(runner.default_model) is reg.quantized_tree(
        runner.default_model
    )
    # a corrupted scale fold (one leaf's scales x64) fails the gate
    broken = ServeRunner(model, params, cfg, max_batch=1,
                         deterministic=True, precision="int8")
    slot = broken._slot(broken.default_model)
    hit = [False]

    def corrupt(x):
        if is_quantized_leaf(x) and not hit[0]:
            hit[0] = True
            return {"int8_q": x["int8_q"],
                    "int8_scale": np.asarray(x["int8_scale"]) * 64.0}
        return x

    slot.predictor.params = jax.tree_util.tree_map(
        corrupt, jax.device_get(slot.predictor.params),
        is_leaf=is_quantized_leaf,
    )
    assert hit[0]
    with pytest.raises(PrecisionParityError, match="int8"):
        broken.check_parity()
