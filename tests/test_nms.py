"""NMS contract tests: jittable masked NMS vs the greedy numpy oracle."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from mx_rcnn_tpu.ops.nms import batched_class_nms, nms, nms_mask, nms_numpy


def random_dets(rng, n, span=100.0):
    boxes = rng.rand(n, 4).astype(np.float32) * span
    boxes[:, 2:] = boxes[:, :2] + rng.rand(n, 2).astype(np.float32) * span * 0.5 + 1
    scores = rng.rand(n).astype(np.float32)
    return boxes, scores


class TestNmsMask:
    @pytest.mark.parametrize("thresh", [0.3, 0.5, 0.7])
    @pytest.mark.parametrize("n", [1, 17, 200])
    def test_matches_numpy_oracle(self, rng, thresh, n):
        boxes, scores = random_dets(rng, n)
        keep = np.asarray(nms_mask(jnp.array(boxes), jnp.array(scores), thresh))
        dets = np.hstack([boxes, scores[:, None]])
        expected = set(nms_numpy(dets, thresh))
        assert set(np.where(keep)[0]) == expected

    @pytest.mark.parametrize("n", [17, 200])
    def test_sorted_input_fast_path_matches(self, rng, n):
        # the propose() path feeds top_k output with sorted_input=True;
        # it must agree with the general path on pre-sorted data
        boxes, scores = random_dets(rng, n)
        order = np.argsort(-scores)
        boxes, scores = boxes[order], scores[order]
        valid = jnp.arange(n) < (n - 3)
        a = np.asarray(
            nms_mask(jnp.array(boxes), jnp.array(scores), 0.5, valid)
        )
        b = np.asarray(
            nms_mask(
                jnp.array(boxes), jnp.array(scores), 0.5, valid,
                sorted_input=True,
            )
        )
        assert (a == b).all()

    def test_invalid_never_suppresses(self, rng):
        # an invalid high-score box overlapping a valid one must not kill it
        boxes = np.array([[0, 0, 10, 10], [1, 1, 11, 11]], dtype=np.float32)
        scores = np.array([0.9, 0.5], dtype=np.float32)
        valid = np.array([False, True])
        keep = np.asarray(
            nms_mask(jnp.array(boxes), jnp.array(scores), 0.3, jnp.array(valid))
        )
        assert keep.tolist() == [False, True]

    def test_jit_stable(self, rng):
        boxes, scores = random_dets(rng, 64)
        f = jax.jit(lambda b, s: nms_mask(b, s, 0.5))
        a = np.asarray(f(jnp.array(boxes), jnp.array(scores)))
        b = np.asarray(nms_mask(jnp.array(boxes), jnp.array(scores), 0.5))
        assert (a == b).all()


class TestNmsTopK:
    def test_fixed_shape_and_order(self, rng):
        boxes, scores = random_dets(rng, 100)
        out_boxes, out_scores, out_valid = nms(
            jnp.array(boxes), jnp.array(scores), 0.5, max_out=32
        )
        assert out_boxes.shape == (32, 4)
        s = np.asarray(out_scores)
        v = np.asarray(out_valid)
        # survivors come first, descending
        assert (np.diff(s[v]) <= 1e-6).all()
        # padding rows are zeroed
        assert (np.asarray(out_boxes)[~v] == 0).all()

    def test_padding_when_few_survivors(self):
        # two heavily-overlapping boxes → 1 survivor, 7 pad rows
        boxes = jnp.array([[0, 0, 10, 10], [0, 0, 10, 11]], dtype=jnp.float32)
        scores = jnp.array([0.9, 0.8])
        _, _, valid = nms(boxes, scores, 0.5, max_out=8)
        assert int(valid.sum()) == 1

    def test_batched_class_nms(self, rng):
        C, N = 4, 50
        boxes = np.stack([random_dets(rng, N)[0] for _ in range(C)])
        scores = rng.rand(C, N).astype(np.float32)
        ob, os_, ov = batched_class_nms(jnp.array(boxes), jnp.array(scores), 0.3, 16)
        assert ob.shape == (C, 16, 4)
        for c in range(C):
            dets = np.hstack([boxes[c], scores[c][:, None]])
            expected = nms_numpy(dets, 0.3)[:16]
            got_scores = np.sort(np.asarray(os_[c])[np.asarray(ov[c])])[::-1]
            exp_scores = np.sort(scores[c][expected])[::-1]
            np.testing.assert_allclose(got_scores, exp_scores, rtol=1e-6)
