"""Native host ops (hostops.c via ctypes) vs the numpy oracles.

Reference roles: ``rcnn/cython/cpu_nms.pyx`` and ``rcnn/cython/bbox.pyx``.
"""

import numpy as np

from mx_rcnn_tpu.native import hostops
from mx_rcnn_tpu.ops.nms import nms_numpy


def _random_dets(rng, n, span=400.0, wh=80.0):
    ctr = rng.rand(n, 2) * span
    half = (rng.rand(n, 2) * wh + 4) / 2
    boxes = np.hstack([ctr - half, ctr + half]).astype(np.float32)
    scores = rng.rand(n, 1).astype(np.float32)
    return np.hstack([boxes, scores])


def test_native_lib_builds():
    # this image ships a toolchain; the C path must actually engage here
    # (the numpy fallback is for compiler-less deployments)
    assert hostops._lib() is not None


def test_nms_matches_numpy_oracle():
    rng = np.random.RandomState(0)
    for n in (1, 7, 100, 1000):
        for thresh in (0.3, 0.5, 0.7):
            dets = _random_dets(rng, n)
            assert hostops.nms_host(dets, thresh) == nms_numpy(dets, thresh)


def test_nms_tie_order_matches_oracle():
    # equal scores: the oracle's argsort[::-1] visits higher index first
    dets = np.array(
        [
            [0, 0, 10, 10, 0.5],
            [100, 100, 110, 110, 0.5],
            [1, 1, 11, 11, 0.5],
        ],
        np.float32,
    )
    assert hostops.nms_host(dets, 0.5) == nms_numpy(dets, 0.5)


def test_nms_empty_and_all_overlapping():
    assert hostops.nms_host(np.zeros((0, 5), np.float32), 0.3) == []
    dets = np.array(
        [[0, 0, 10, 10, 0.9], [0, 0, 10, 10, 0.8], [0, 0, 10, 10, 0.7]],
        np.float32,
    )
    assert hostops.nms_host(dets, 0.5) == [0]


def test_bbox_overlaps_matches_numpy():
    rng = np.random.RandomState(1)
    boxes = _random_dets(rng, 50)[:, :4]
    query = _random_dets(rng, 20)[:, :4]
    got = hostops.bbox_overlaps_host(boxes, query)

    ba = (boxes[:, 2] - boxes[:, 0] + 1) * (boxes[:, 3] - boxes[:, 1] + 1)
    qa = (query[:, 2] - query[:, 0] + 1) * (query[:, 3] - query[:, 1] + 1)
    iw = np.maximum(
        np.minimum(boxes[:, None, 2], query[None, :, 2])
        - np.maximum(boxes[:, None, 0], query[None, :, 0]) + 1,
        0,
    )
    ih = np.maximum(
        np.minimum(boxes[:, None, 3], query[None, :, 3])
        - np.maximum(boxes[:, None, 1], query[None, :, 1]) + 1,
        0,
    )
    inter = iw * ih
    want = inter / (ba[:, None] + qa[None, :] - inter)
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)
    assert got.shape == (50, 20)


def test_bbox_overlaps_empty():
    assert hostops.bbox_overlaps_host(
        np.zeros((0, 4), np.float32), np.zeros((3, 4), np.float32)
    ).shape == (0, 3)


def test_numpy_fallback_matches_native(monkeypatch):
    # compiler-less deployments take the numpy branch; it must agree
    rng = np.random.RandomState(2)
    dets = _random_dets(rng, 200)
    want_nms = hostops.nms_host(dets, 0.5)
    want_ov = hostops.bbox_overlaps_host(dets[:, :4], dets[:50, :4])
    monkeypatch.setattr(hostops, "_LIB", None)
    monkeypatch.setattr(hostops, "_TRIED", True)
    assert hostops.nms_host(dets, 0.5) == want_nms
    np.testing.assert_allclose(
        hostops.bbox_overlaps_host(dets[:, :4], dets[:50, :4]),
        want_ov, rtol=1e-5, atol=1e-6,
    )
