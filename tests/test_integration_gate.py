"""The train→eval mAP integration gate (VERDICT r1 #3).

Trains the small-shape flagship architecture on 8 synthetic images and
runs the FULL eval stack (Predictor → im_detect → per-class NMS →
evaluate_detections) on the same images; overfitting must reach high mAP.
This is the only test that exercises the proposal→im_detect→eval seams
end to end.
"""

import numpy as np
import pytest

from mx_rcnn_tpu.tools.integration_gate import run_gate

# up to ~52 min solo on this 1-core box (PARITY round-4 notes)
pytestmark = [pytest.mark.slow, pytest.mark.deadline(7200)]


def test_overfit_reaches_high_map():
    # 500-step budget, lr decays 10x at 250, early-stops at the target
    # (measured trajectory: ~0.42@100, ~0.72@200, ~0.92@300)
    out = run_gate(num_images=8, steps=500, eval_every=100, target=0.8)
    assert np.isfinite(out["mAP"])
    assert out["mAP"] >= 0.8, f"integration gate failed: {out}"
