"""Elastic replica autoscaling (ISSUE 16): breaker, controller, pool
elasticity, zero-loss scale-down, stop ordering, trace shapes.

Layered cheapest-first:

* pure ScaleBreaker with an injected clock: cooldown, flap-doubling,
  age-out;
* AutoScaler driven synchronously (``tick(now=...)``) against a fake
  pool + injected signals: the samples streak, growth/shrink decisions,
  breaker suppression on an oscillating signal, bounded convergence;
* a REAL ReplicaPool on numpy runner stubs: copy-on-write add/remove
  semantics, the replicas[0] anchor, and the headline guarantee — a
  scale-down in the middle of live load loses zero requests and the
  responses are byte-identical to a fixed-size control run;
* engine integration: ``attach_autoscaler`` wiring, the stop-ordering
  regression (autoscaler joined BEFORE pool teardown), and the
  trace-driven loadgen shapes (diurnal + flash crowd).

Every test runs with the lock-order checker armed, same as
tests/test_replica.py.
"""

import threading
import time
from types import SimpleNamespace

import numpy as np
import pytest

from mx_rcnn_tpu.serve.autoscaler import AutoScaler, ScaleBreaker, ScalePolicy
from mx_rcnn_tpu.serve.batcher import Request
from mx_rcnn_tpu.serve.buckets import BucketLadder, CompileCache
from mx_rcnn_tpu.serve.engine import ServingEngine
from mx_rcnn_tpu.serve.loadgen import (
    diurnal_arrivals,
    flash_arrivals,
    run_load,
)
from mx_rcnn_tpu.serve.replica import HealthPolicy
from mx_rcnn_tpu.serve.router import ReplicaPool


@pytest.fixture(autouse=True)
def _lock_order_check(monkeypatch):
    from mx_rcnn_tpu.analysis import lockcheck

    monkeypatch.setenv("MX_RCNN_LOCK_CHECK", "1")
    lockcheck.reset()
    yield


LADDER = ((32, 32), (48, 64))

FAST = HealthPolicy(
    stall_timeout=0.5,
    fail_threshold=2,
    breaker_backoff=0.05,
    breaker_max_backoff=0.2,
    flap_window=10.0,
)


class FakeRunner:
    """Runner stub (tests/test_replica.py shape): per-slot digest is a
    pure function of the pixels, so byte-identity across pool sizes is a
    meaningful assertion."""

    def __init__(self, index: int = 0, service_s: float = 0.0):
        self.index = index
        self.service_s = service_s
        self.ladder = BucketLadder(LADDER)
        self.max_batch = 2
        self.cfg = None
        self.compile_cache = CompileCache()

    def warmup(self) -> int:
        for bh, bw in self.ladder:
            self.compile_cache.record(((self.max_batch, bh, bw, 3), "f32"))
        return self.compile_cache.misses

    def make_request(self, im, deadline=None) -> Request:
        h, w = im.shape[:2]
        bh, bw = self.ladder.select(h, w)
        canvas = np.zeros((bh, bw, 3), np.float32)
        canvas[:h, :w] = im
        return Request(
            image=canvas,
            im_info=np.array([h, w, 1.0], np.float32),
            orig_hw=(h, w),
            bucket=(bh, bw),
            deadline=deadline,
        )

    def assemble(self, requests):
        images = [r.image for r in requests]
        while len(images) < self.max_batch:
            images.append(images[0])
        return {"images": np.stack(images)}

    def run(self, batch):
        if self.service_s:
            time.sleep(self.service_s)
        self.compile_cache.record((batch["images"].shape, "f32"))
        im = batch["images"].astype(np.float64)
        return {"digest": im.sum(axis=(1, 2, 3))}

    def detections_for(self, out, batch, index, orig_hw=None, thresh=None):
        return [np.array([out["digest"][index]])]


def make_factory(service_s: float = 0.0):
    def factory(index: int) -> FakeRunner:
        return FakeRunner(index, service_s=service_s)

    return factory


def image(i: int, h: int = 24, w: int = 24) -> np.ndarray:
    rng = np.random.RandomState(1000 + i)
    return rng.rand(h, w, 3).astype(np.float32)


class FakePool:
    """Just enough pool surface for AutoScaler decision tests: a
    replicas list plus add/remove with the copy-on-write contract."""

    def __init__(self, n: int):
        self.replicas = [SimpleNamespace(routable=True) for _ in range(n)]

    def add_replica(self):
        r = SimpleNamespace(routable=True)
        self.replicas = self.replicas + [r]
        return r

    def remove_replica(self, replica=None, timeout=5.0):
        if len(self.replicas) <= 1:
            return None
        victim = self.replicas[-1]
        self.replicas = self.replicas[:-1]
        return victim


def sig(depth, healthy, p99=None):
    return {"queue_depth": depth, "healthy": healthy, "p99_ms": p99}


# ------------------------------------------------------------- breaker
class TestScaleBreaker:
    def test_cooldown_gates_next_event(self):
        b = ScaleBreaker(cooldown=1.0, flap_window=5.0)
        assert b.allow(0.0)
        b.note(0.0, "up")
        assert not b.allow(0.5)
        assert b.suppressed == 1
        assert b.allow(1.5)

    def test_reversal_inside_window_doubles_backoff(self):
        b = ScaleBreaker(cooldown=1.0, flap_window=5.0, max_backoff=8.0)
        b.note(0.0, "up")
        b.note(2.0, "down")  # reversal 2s later, inside the 5s window
        assert b.flaps == 1
        assert b.snapshot()["backoff_s"] == 2.0
        b.note(4.0, "up")
        assert b.flaps == 2
        assert b.snapshot()["backoff_s"] == 4.0
        # same-direction events are not flaps
        b.note(6.0, "up")
        assert b.flaps == 2

    def test_backoff_caps_at_max(self):
        b = ScaleBreaker(cooldown=3.0, flap_window=100.0, max_backoff=4.0)
        for t, d in [(0, "up"), (10, "down"), (20, "up"), (30, "down")]:
            b.note(float(t), d)
        assert b.snapshot()["backoff_s"] == 4.0

    def test_clean_window_ages_backoff_out(self):
        b = ScaleBreaker(cooldown=1.0, flap_window=5.0)
        b.note(0.0, "up")
        b.note(2.0, "down")
        assert b.snapshot()["backoff_s"] == 2.0
        # a full flap_window with no further flap closes the breaker
        assert b.allow(10.0)
        assert b.snapshot()["backoff_s"] == 1.0


# ---------------------------------------------------------- controller
class TestAutoScalerDecisions:
    def make(self, n=1, **policy_over):
        kw = dict(min_replicas=1, max_replicas=4, samples=3,
                  cooldown=0.0, flap_window=0.0)
        kw.update(policy_over)
        pool = FakePool(n)
        scaler = AutoScaler(pool, policy=ScalePolicy(**kw))
        return pool, scaler

    def drive(self, scaler, signals, t0=100.0, dt=1.0):
        actions = []
        now = t0
        for s in signals:
            scaler._signal_fn = lambda s=s: s
            actions.append(scaler.tick(now=now))
            now += dt
        return actions

    def test_streak_required_before_growing(self):
        pool, scaler = self.make(n=1, samples=3)
        acts = self.drive(scaler, [sig(100, 1)] * 3)
        # tick1 starts the streak, tick2 extends, tick3 acts
        assert acts == [None, None, "up"]
        assert len(pool.replicas) == 2

    def test_interrupted_streak_resets(self):
        pool, scaler = self.make(n=1, samples=3)
        acts = self.drive(
            scaler,
            [sig(100, 1), sig(100, 1), sig(1, 1), sig(100, 1), sig(100, 1)],
        )
        # the calm tick broke the streak; two more up-ticks are not
        # enough to act again
        assert acts == [None] * 5
        assert len(pool.replicas) == 1

    def test_shrinks_to_min_on_idle(self):
        pool, scaler = self.make(n=3, samples=2)
        self.drive(scaler, [sig(0, 3)] * 10)
        assert len(pool.replicas) == 1
        assert scaler.scale_downs == 2

    def test_respects_max_replicas(self):
        pool, scaler = self.make(n=1, samples=2, max_replicas=2)
        self.drive(scaler, [sig(1000, 1)] * 10)
        assert len(pool.replicas) == 2
        assert scaler.scale_ups == 1

    def test_p99_slo_triggers_growth(self):
        pool, scaler = self.make(n=1, samples=2, p99_slo_ms=100.0)
        # queue is calm but the interactive p99 is blown
        self.drive(scaler, [sig(0, 1, p99=500.0)] * 3)
        assert len(pool.replicas) == 2

    def test_oscillating_signal_is_damped(self):
        # naive control would flap every few ticks; the breaker must
        # bound the event count and log the suppression
        pool, scaler = self.make(
            n=2, samples=2, max_replicas=4,
            cooldown=0.5, flap_window=100.0, max_backoff=4.0,
        )
        script = ([sig(100, 2)] * 3 + [sig(0, 2)] * 3) * 10
        self.drive(scaler, script, dt=0.1)
        snap = scaler.snapshot()
        total_events = scaler.scale_ups + scaler.scale_downs
        assert total_events <= 6  # vs 20 naive reversals
        assert snap["breaker"]["flaps"] >= 1
        assert snap["breaker"]["suppressed"] >= 5
        assert 1 <= len(pool.replicas) <= 4

    def test_converges_without_flapping_on_sustained_load(self):
        pool, scaler = self.make(n=1, samples=2, max_replicas=3)
        self.drive(scaler, [sig(500, len(pool.replicas))] * 20)
        assert len(pool.replicas) == 3
        assert scaler.scale_ups == 2
        assert scaler.snapshot()["breaker"]["flaps"] == 0
        # events log carries the audit trail
        assert [e["action"] for e in scaler.snapshot()["events"]] \
            == ["up", "up"]


# ------------------------------------------------------- pool elasticity
class TestPoolElasticity:
    def test_add_replica_warms_and_serves(self):
        pool = ReplicaPool(make_factory(), 1, policy=FAST)
        try:
            pool.warmup()
            r = pool.add_replica()
            t_end = time.monotonic() + 10.0
            while not r.routable and time.monotonic() < t_end:
                time.sleep(0.01)
            assert r.routable
            assert len(pool.replicas) == 2
            assert pool.replicas[-1] is r
            # fresh index, not a reuse of an existing one
            assert r.index == 1
        finally:
            pool.close()

    def test_remove_replica_never_strands_the_anchor(self):
        pool = ReplicaPool(make_factory(), 2, policy=FAST)
        try:
            pool.warmup()
            anchor = pool.replicas[0]
            assert pool.remove_replica(anchor) is None  # refuses [0]
            victim = pool.remove_replica()
            assert victim is not None and victim is not anchor
            assert len(pool.replicas) == 1
            assert pool.remove_replica() is None  # size-1 floor
        finally:
            pool.close()

    def test_zero_loss_scale_down_byte_identical(self):
        images = [image(i) for i in range(40)]

        def run(shrink: bool):
            pool = ReplicaPool(make_factory(service_s=0.004), 2,
                               policy=FAST)
            engine = ServingEngine(pool, max_linger=0.0, max_queue=128,
                                   in_flight=1)
            try:
                with engine:
                    futs = [engine.submit(im) for im in images]
                    if shrink:
                        victim = pool.remove_replica()
                        assert victim is not None
                    results = [f.result(timeout=30.0) for f in futs]
            finally:
                pool.close()
            return results, engine.snapshot()

        fixed, _ = run(shrink=False)
        shrunk, snap = run(shrink=True)
        # zero loss: every request completed...
        assert snap["requests"]["completed"] == len(images)
        assert snap["requests"]["failed"] == 0
        # ...and the responses are byte-identical to the control run
        for a, b in zip(fixed, shrunk):
            assert len(a) == len(b)
            for ca, cb in zip(a, b):
                np.testing.assert_array_equal(ca, cb)


# --------------------------------------------------- engine integration
class TestEngineAutoscaler:
    def test_attach_requires_pool_path(self):
        engine = ServingEngine(FakeRunner(), max_linger=0.0)
        with engine:
            with pytest.raises(RuntimeError):
                engine.attach_autoscaler()

    def test_attach_and_real_signals(self):
        pool = ReplicaPool(make_factory(), 1, policy=FAST)
        engine = ServingEngine(pool, max_linger=0.0)
        try:
            with engine:
                scaler = engine.attach_autoscaler(
                    policy=ScalePolicy(max_replicas=2), start=False
                )
                s = scaler.signals()
                assert s["queue_depth"] == 0
                assert s["healthy"] == 1
                assert engine.snapshot()["autoscaler"]["replicas"] == 1
        finally:
            pool.close()

    def test_stop_joins_autoscaler_before_pool_teardown(self):
        # regression (ISSUE 16 satellite): engine.stop must join the
        # controller BEFORE tearing the pool down, otherwise a scale-up
        # firing mid-shutdown races pool.close — same interlock family
        # as the cancel_swaps-first ordering from the registry
        pool = ReplicaPool(make_factory(), 1, policy=FAST)
        engine = ServingEngine(pool, max_linger=0.0)
        with engine:
            scaler = engine.attach_autoscaler(
                policy=ScalePolicy(max_replicas=3, interval=0.01,
                                   samples=1, cooldown=0.0)
            )
            assert scaler.running
        # engine.__exit__ ran stop(): the controller thread is joined,
        # not orphaned, and no further scale events can fire
        assert not scaler.running
        assert not any(
            t.name == "autoscaler" and t.is_alive()
            for t in threading.enumerate()
        )
        pool.close()

    def test_stop_is_idempotent_with_autoscaler(self):
        pool = ReplicaPool(make_factory(), 1, policy=FAST)
        engine = ServingEngine(pool, max_linger=0.0)
        engine.start()
        engine.attach_autoscaler(policy=ScalePolicy(max_replicas=2))
        engine.stop()
        engine.stop()
        assert not engine.autoscaler.running
        pool.close()


# ------------------------------------------------------- trace shapes
class TestTraces:
    def test_diurnal_arrivals_shape(self):
        arr = diurnal_arrivals(200, lo_rps=5.0, hi_rps=50.0, seed=3)
        assert len(arr) == 200
        assert all(b >= a for a, b in zip(arr, arr[1:]))
        assert arr[0] >= 0.0
        # deterministic per seed
        assert arr == diurnal_arrivals(200, lo_rps=5.0, hi_rps=50.0, seed=3)
        assert arr != diurnal_arrivals(200, lo_rps=5.0, hi_rps=50.0, seed=4)
        # the ramp is real: arrivals cluster where the rate peaks, so
        # the middle third of the span holds more than a third of them
        span = arr[-1]
        mid = [t for t in arr if span / 3 <= t <= 2 * span / 3]
        assert len(mid) > len(arr) / 3

    def test_flash_arrivals_compress_the_spike(self):
        arr = flash_arrivals(300, base_rps=10.0, flash_frac=0.5,
                             flash_at=0.5, seed=1)
        assert len(arr) == 300
        assert all(b >= a for a, b in zip(arr, arr[1:]))
        gaps = np.diff(np.asarray(arr))
        # flash gaps (10x rate) are far tighter than base gaps
        assert np.median(gaps[:100]) > 3 * np.median(gaps[170:270])

    def test_run_load_trace_and_tenants(self):
        engine = ServingEngine(FakeRunner(), max_linger=0.0, max_queue=256)
        arr = flash_arrivals(24, base_rps=200.0, flash_frac=0.5, seed=2)
        with engine:
            report = run_load(
                engine, num_requests=24, concurrency=4,
                sizes=((24, 24),), seed=0,
                tenants=["acme", "beta"], arrivals=arr,
            )
        assert report["outcomes"]["ok"] == 24
        assert set(report["tenants"]) == {"acme", "beta"}
        per_tenant = report["tenant_outcomes"]
        assert sum(v["ok"] for v in per_tenant.values()) == 24
        assert report["trace"]["arrivals"] == 24
        assert report["trace"]["span_s"] > 0
