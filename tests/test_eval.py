"""Tests for VOC AP and the pure-numpy COCO bbox protocol."""

import numpy as np
import pytest

from mx_rcnn_tpu.eval.coco_eval import COCOEvalBbox
from mx_rcnn_tpu.eval.voc_eval import voc_ap, voc_eval


class TestVocAp:
    def test_perfect_pr(self):
        rec = np.array([0.5, 1.0])
        prec = np.array([1.0, 1.0])
        assert voc_ap(rec, prec, use_07_metric=False) == pytest.approx(1.0)
        assert voc_ap(rec, prec, use_07_metric=True) == pytest.approx(1.0)

    def test_known_07_value(self):
        # single det covering half the gts at full precision
        rec = np.array([0.5])
        prec = np.array([1.0])
        # 07 metric: max prec at t<=0.5 is 1 (6 points), 0 above → 6/11
        assert voc_ap(rec, prec, True) == pytest.approx(6 / 11)
        # integral metric: area = 0.5
        assert voc_ap(rec, prec, False) == pytest.approx(0.5)


class TestVocEval:
    def annots(self):
        return {
            "img0": {
                "boxes": np.array([[0, 0, 10, 10], [50, 50, 80, 80]], float),
                "gt_classes": np.array([1, 1]),
                "difficult": np.array([False, False]),
            },
            "img1": {
                "boxes": np.array([[20, 20, 40, 40]], float),
                "gt_classes": np.array([1]),
                "difficult": np.array([False]),
            },
        }

    def test_perfect_detection(self):
        dets = {
            "img0": np.array(
                [[0, 0, 10, 10, 0.9], [50, 50, 80, 80, 0.8]], float
            ),
            "img1": np.array([[20, 20, 40, 40, 0.95]], float),
        }
        rec, prec, ap = voc_eval(dets, self.annots(), 1)
        assert ap == pytest.approx(1.0)
        assert rec[-1] == pytest.approx(1.0)

    def test_duplicate_detection_is_fp(self):
        dets = {
            "img0": np.array(
                [[0, 0, 10, 10, 0.9], [1, 1, 10, 10, 0.85]], float
            ),
            "img1": np.zeros((0, 5)),
        }
        rec, prec, ap = voc_eval(dets, self.annots(), 1)
        # second det matches an already-matched gt → FP
        assert prec[-1] == pytest.approx(0.5)

    def test_difficult_not_counted(self):
        ann = self.annots()
        ann["img0"]["difficult"] = np.array([True, False])
        dets = {
            "img0": np.array([[0, 0, 10, 10, 0.9]], float),  # matches difficult
            "img1": np.zeros((0, 5)),
        }
        rec, prec, ap = voc_eval(dets, ann, 1)
        # det on difficult gt → ignored entirely; npos excludes difficult
        assert len(rec) == 1 and rec[0] == 0.0

    def test_low_iou_is_fp(self):
        dets = {
            "img0": np.array([[100, 100, 120, 120, 0.9]], float),
            "img1": np.zeros((0, 5)),
        }
        rec, prec, ap = voc_eval(dets, self.annots(), 1)
        assert ap == 0.0


def coco_dataset():
    images = [{"id": 1, "width": 200, "height": 200},
              {"id": 2, "width": 200, "height": 200}]
    cats = [{"id": 7, "name": "cat"}, {"id": 9, "name": "dog"}]
    anns = [
        {"id": 1, "image_id": 1, "category_id": 7, "bbox": [10, 10, 50, 50],
         "area": 2500, "iscrowd": 0},
        {"id": 2, "image_id": 1, "category_id": 9, "bbox": [100, 100, 40, 40],
         "area": 1600, "iscrowd": 0},
        {"id": 3, "image_id": 2, "category_id": 7, "bbox": [20, 20, 60, 60],
         "area": 3600, "iscrowd": 0},
    ]
    return {"images": images, "annotations": anns, "categories": cats}


class TestCocoEval:
    def test_perfect_detections(self):
        ds = coco_dataset()
        results = [
            {"image_id": a["image_id"], "category_id": a["category_id"],
             "bbox": list(a["bbox"]), "score": 0.9}
            for a in ds["annotations"]
        ]
        stats = COCOEvalBbox(ds, results).evaluate(verbose=False)
        assert stats["AP"] == pytest.approx(1.0)
        assert stats["AP50"] == pytest.approx(1.0)
        assert stats["AR_100"] == pytest.approx(1.0)

    def test_no_detections(self):
        stats = COCOEvalBbox(coco_dataset(), []).evaluate(verbose=False)
        assert stats["AP"] == pytest.approx(0.0)

    def test_halfway_iou_counts_at_50_not_95(self):
        ds = coco_dataset()
        # shift the box so IoU ≈ 0.68: TP at 0.5/0.65, FP at 0.7+
        results = [
            {"image_id": 1, "category_id": 7, "bbox": [20, 10, 50, 50], "score": 0.9},
        ]
        stats = COCOEvalBbox(ds, results).evaluate(verbose=False)
        assert stats["AP50"] > 0
        assert stats["AP75"] == pytest.approx(0.0)
        assert 0 < stats["AP"] < stats["AP50"]

    def test_crowd_gt_is_ignore(self):
        ds = coco_dataset()
        ds["annotations"].append(
            {"id": 4, "image_id": 2, "category_id": 9,
             "bbox": [0, 0, 150, 150], "area": 22500, "iscrowd": 1}
        )
        # det inside the crowd region, class dog, scored ABOVE the real
        # det: if crowd-ignore works it's neither TP nor FP; if it were
        # counted FP at rank 1 the precision envelope would halve dog AP
        results = [
            {"image_id": 2, "category_id": 9, "bbox": [10, 10, 30, 30], "score": 0.9},
            {"image_id": 1, "category_id": 9, "bbox": [100, 100, 40, 40], "score": 0.8},
            # perfect cat detections so the category mean isolates dog
            {"image_id": 1, "category_id": 7, "bbox": [10, 10, 50, 50], "score": 0.9},
            {"image_id": 2, "category_id": 7, "bbox": [20, 20, 60, 60], "score": 0.9},
        ]
        stats = COCOEvalBbox(ds, results).evaluate(verbose=False)
        assert stats["AP"] == pytest.approx(1.0, abs=1e-6)

    def test_small_area_bucket(self):
        ds = coco_dataset()
        ds["annotations"].append(
            {"id": 5, "image_id": 2, "category_id": 9, "bbox": [5, 5, 10, 10],
             "area": 100, "iscrowd": 0}
        )
        results = [
            {"image_id": 2, "category_id": 9, "bbox": [5, 5, 10, 10], "score": 0.9}
        ]
        stats = COCOEvalBbox(ds, results).evaluate(verbose=False)
        assert stats["AP_small"] == pytest.approx(1.0)


class TestCOCOSegmEval:
    """segm protocol via the native RLE library (iou_type='segm')."""

    def _ds(self):
        from mx_rcnn_tpu.native import rle

        images = [{"id": 1, "height": 40, "width": 40}]
        cats = [{"id": 1}]
        # gt: 20x20 square as a polygon
        anns = [{
            "id": 1, "image_id": 1, "category_id": 1,
            "bbox": [5, 5, 20, 20], "area": 400, "iscrowd": 0,
            "segmentation": [[5, 5, 25, 5, 25, 25, 5, 25]],
        }]
        return {"images": images, "annotations": anns, "categories": cats}

    def test_perfect_mask_ap1(self):
        from mx_rcnn_tpu.eval.coco_eval import COCOEvalBbox
        from mx_rcnn_tpu.native import rle

        m = np.zeros((40, 40), np.uint8)
        m[5:25, 5:25] = 1
        results = [{
            "image_id": 1, "category_id": 1, "bbox": [5, 5, 20, 20],
            "score": 0.9, "segmentation": rle.encode(m),
        }]
        stats = COCOEvalBbox(self._ds(), results, iou_type="segm").evaluate(
            verbose=False
        )
        assert stats["AP"] == pytest.approx(1.0)

    def test_half_mask_scores_lower(self):
        from mx_rcnn_tpu.eval.coco_eval import COCOEvalBbox
        from mx_rcnn_tpu.native import rle

        half = np.zeros((40, 40), np.uint8)
        half[5:25, 5:15] = 1  # IoU 0.5 vs the gt square
        results = [{
            "image_id": 1, "category_id": 1, "bbox": [5, 5, 20, 20],
            "score": 0.9, "segmentation": rle.encode(half),
        }]
        stats = COCOEvalBbox(self._ds(), results, iou_type="segm").evaluate(
            verbose=False
        )
        # matches at IoU .5 only → AP ≈ 1/10 of thresholds
        assert 0.05 < stats["AP"] < 0.2
        assert stats["AP50"] == pytest.approx(1.0)

    def test_paste_mask_roundtrip(self):
        from mx_rcnn_tpu.eval.segm import mask_to_rle, paste_mask
        from mx_rcnn_tpu.native import rle

        prob = np.ones((28, 28), np.float32)
        out = paste_mask(prob, np.array([10, 12, 19, 21]), 40, 40)
        assert out[12:22, 10:20].all()
        assert out.sum() == 10 * 10
        r = mask_to_rle(prob, np.array([10, 12, 19, 21]), 40, 40)
        np.testing.assert_array_equal(rle.decode(r), out)


class TestBatchedPredEval:
    def test_batched_matches_batch1(self):
        """batch_size>1 eval (same-bucket device batching, a
        beyond-reference upgrade) must reproduce the batch=1 detections
        image for image."""
        import dataclasses as dc

        import jax

        from mx_rcnn_tpu.core.tester import Predictor, pred_eval
        from mx_rcnn_tpu.data.loader import TestLoader
        from mx_rcnn_tpu.data.synthetic import SyntheticDataset
        from mx_rcnn_tpu.models import FasterRCNN
        from tests.test_model import tiny_cfg

        cfg = tiny_cfg()
        cfg = cfg.replace(
            SHAPE_BUCKETS=((128, 128),),
            TEST=dc.replace(cfg.TEST, SCORE_THRESH=0.0),
            dataset=dc.replace(
                cfg.dataset, NUM_CLASSES=4, SCALES=((128, 128),), MAX_GT_BOXES=8
            ),
        )
        imdb = SyntheticDataset(
            num_images=5, num_classes=4, image_size=(128, 128), max_boxes=2
        )
        roidb = imdb.gt_roidb()
        model = FasterRCNN(cfg)
        rec = roidb[0]
        import numpy as np

        from mx_rcnn_tpu.data.loader import _orientation_bucket, make_batch

        b0 = make_batch([rec], cfg, _orientation_bucket(rec, cfg.SHAPE_BUCKETS))
        params = model.init(
            {"params": jax.random.key(0), "sampling": jax.random.key(1)},
            train=True, **b0,
        )["params"]

        class NoEval:
            num_classes = imdb.num_classes
            classes = imdb.classes

            def evaluate_detections(self, all_boxes, **kw):
                return {}

        predictor = Predictor(model, params)
        ab1, _ = pred_eval(predictor, TestLoader(roidb, cfg), NoEval(), cfg)
        abN, _ = pred_eval(
            predictor, TestLoader(roidb, cfg, batch_size=2), NoEval(), cfg
        )
        for j in range(1, imdb.num_classes):
            for i in range(len(roidb)):
                assert ab1[j][i].shape == abN[j][i].shape, (j, i)
                # batch-1 vs batched convs differ at the 1e-3 level (XLA
                # picks different conv schedules per batch size)
                np.testing.assert_allclose(
                    abN[j][i], ab1[j][i], rtol=2e-3, atol=2e-3,
                    err_msg=f"class {j} image {i}",
                )
