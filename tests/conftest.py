"""Test harness: run everything on CPU with 8 virtual devices.

This is the TPU-world "fake backend" the reference never had (SURVEY §5.1):
multi-chip sharding paths compile and execute on 8 XLA host devices, so DP
correctness is tested without hardware.

Note: this environment's sitecustomize registers the axon TPU plugin and
hard-sets ``jax_platforms`` at interpreter start (before conftest), so
plain ``JAX_PLATFORMS=cpu`` is ignored — we must override via jax.config
and drop any already-initialized backends.
"""

import os

os.environ["XLA_FLAGS"] = (
    os.environ.get("XLA_FLAGS", "") + " --xla_force_host_platform_device_count=8"
)
# persistent compile cache: recompiles across test runs are the dominant
# cost on this 1-core machine
os.environ.setdefault("JAX_COMPILATION_CACHE_DIR", "/tmp/jax_cache")
os.environ.setdefault("JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS", "1")
os.environ.setdefault("JAX_PERSISTENT_CACHE_MIN_ENTRY_SIZE_BYTES", "0")

import jax

jax.config.update("jax_platforms", "cpu")
try:
    from jax.extend.backend import clear_backends

    clear_backends()
except Exception:  # pragma: no cover - backends not initialized yet
    pass

assert jax.devices()[0].platform == "cpu", "tests must run on host CPU"
assert len(jax.devices()) == 8, "expected 8 virtual CPU devices"

import numpy as np
import pytest

# ---------------------------------------------------------------------------
# Per-test wall-clock deadline (VERDICT r4 weak #6): a hang must fail
# loudly, not be indistinguishable from a slow compile.  pytest-timeout is
# not in this image, so a WATCHDOG THREAD (pytest-timeout's "thread"
# method): a SIGALRM guard can't fire while the main thread is wedged
# inside native XLA code (the signal is only delivered at a bytecode
# boundary), and it wouldn't cover fixture setup — where the big
# model-init compiles live.  The watchdog wraps the WHOLE runtest
# protocol (setup+call+teardown), dumps every thread's stack on expiry,
# and os._exit(70)s: the run dies loudly at the offending test instead
# of stalling forever.  Deadlines: generous default for cold 1-core
# compiles; long tests carry ``@pytest.mark.deadline(n)`` (0 disables);
# override globally with MX_RCNN_TEST_TIMEOUT.
# ---------------------------------------------------------------------------
_DEADLINE = int(os.environ.get("MX_RCNN_TEST_TIMEOUT", "900"))


def pytest_configure(config):
    config.addinivalue_line(
        "markers",
        "slow: heavy compile-bound test, excluded from `make test-fast`",
    )
    config.addinivalue_line(
        "markers",
        "deadline(secs): per-test wall-clock deadline override (0 = none)",
    )


@pytest.hookimpl(wrapper=True)
def pytest_runtest_protocol(item, nextitem):
    import faulthandler
    import sys
    import threading

    marker = item.get_closest_marker("deadline")
    secs = int(marker.args[0]) if marker else _DEADLINE
    if secs <= 0:
        return (yield)

    def _expired():
        # suspend pytest's capture first (pytest-timeout does the same):
        # with fd-level capture the dump would land in a capture temp
        # file that os._exit discards, leaving exit code 70 and zero
        # diagnostics — the exact silent-hang failure this guard fixes
        try:
            capman = item.config.pluginmanager.getplugin("capturemanager")
            if capman is not None:
                capman.suspend_global_capture(in_=True)
        except Exception:
            pass
        sys.stderr.write(
            f"\n=== DEADLINE: {item.nodeid} exceeded {secs}s — dumping "
            f"all thread stacks and aborting the run (raise with "
            f"@pytest.mark.deadline(n) or MX_RCNN_TEST_TIMEOUT) ===\n"
        )
        faulthandler.dump_traceback(file=sys.stderr)
        sys.stderr.flush()
        os._exit(70)

    watchdog = threading.Timer(secs, _expired)
    watchdog.daemon = True
    watchdog.start()
    try:
        return (yield)
    finally:
        watchdog.cancel()


@pytest.fixture
def rng():
    return np.random.RandomState(0)
