"""Test harness: run everything on CPU with 8 virtual devices.

This is the TPU-world "fake backend" the reference never had (SURVEY §5.1):
multi-chip sharding paths compile and execute on 8 XLA host devices, so DP
correctness is tested without hardware.  Must run before jax is imported.
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = (
    os.environ.get("XLA_FLAGS", "") + " --xla_force_host_platform_device_count=8"
)

import numpy as np
import pytest


@pytest.fixture
def rng():
    return np.random.RandomState(0)
