"""Test harness: run everything on CPU with 8 virtual devices.

This is the TPU-world "fake backend" the reference never had (SURVEY §5.1):
multi-chip sharding paths compile and execute on 8 XLA host devices, so DP
correctness is tested without hardware.

Note: this environment's sitecustomize registers the axon TPU plugin and
hard-sets ``jax_platforms`` at interpreter start (before conftest), so
plain ``JAX_PLATFORMS=cpu`` is ignored — we must override via jax.config
and drop any already-initialized backends.
"""

import os

os.environ["XLA_FLAGS"] = (
    os.environ.get("XLA_FLAGS", "") + " --xla_force_host_platform_device_count=8"
)
# persistent compile cache: recompiles across test runs are the dominant
# cost on this 1-core machine
os.environ.setdefault("JAX_COMPILATION_CACHE_DIR", "/tmp/jax_cache")
os.environ.setdefault("JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS", "1")
os.environ.setdefault("JAX_PERSISTENT_CACHE_MIN_ENTRY_SIZE_BYTES", "0")

import jax

jax.config.update("jax_platforms", "cpu")
try:
    from jax.extend.backend import clear_backends

    clear_backends()
except Exception:  # pragma: no cover - backends not initialized yet
    pass

assert jax.devices()[0].platform == "cpu", "tests must run on host CPU"
assert len(jax.devices()) == 8, "expected 8 virtual CPU devices"

import numpy as np
import pytest


@pytest.fixture
def rng():
    return np.random.RandomState(0)
